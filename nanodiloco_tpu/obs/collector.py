"""Fleet metric collector: scrape /metrics endpoints into time series.

Every tier of this system already EXPOSES gauges — the trainer's
TelemetryServer, each serve replica, the fleet router — but a gauge is
a point in time: nobody watches the fleet OVER time, so nothing can say
"TTFT p95 has been over budget for 40 of the last 60 seconds" (the
question an SLO burn rate asks, obs/slo.py) and an incident leaves no
timeline behind. MegaScale's operability premise (arXiv:2402.15627) is
continuous collection plus cross-component joins; this module is the
collection half, stdlib only, in the repo's own dialect:

- ``parse_exposition`` — a STRUCTURED OpenMetrics parser that
  round-trips ``render_exposition`` (obs/telemetry.py): gauges,
  counters (the family-name / ``_total``-sample split), labeled
  histogram families (cumulative ``_bucket{le=...}`` + ``_count`` /
  ``_sum``), and label values with the three escaped characters
  (``\\``, ``"``, newline) — unescaped in a single pass, because the
  sequential-``str.replace`` shortcut corrupts a literal backslash
  followed by ``n``. ``render_exposition(parse_exposition(text))``
  reproduces ``text`` byte-for-byte for everything this repo emits
  (property-tested), so the scrape path and the exposition path cannot
  drift.
- ``SeriesStore`` — bounded per-series ring buffers of ``(t, value)``
  samples (oldest evicted; a collector watching a week-long run must
  not grow without bound) with the query surface the SLO engine needs:
  windowed samples, windowed mean/max/min, counter ``increase``/
  ``rate`` (positive deltas only, so a process restart reads as a
  reset, not a negative rate), nearest-rank percentiles over a
  window, and the FORECASTING queries the autoscaler acts on
  (``fleet/autoscaler.py``): ``slope`` (Theil-Sen robust trend,
  counter-reset tolerant) and ``forecast_exhaustion`` (seconds until
  a series crosses a floor/ceiling at the current trend).
- ``Collector`` — the scrape loop over named targets. Clock, wall
  clock, sleep, and the HTTP fetch are all injectable (tests script a
  fleet with a fake clock and no sockets; the default fetch is the
  ``serve/client`` wire helper), every sample lands in the store keyed
  ``target:sample`` with the label text kept verbatim
  (``r0:nanodiloco_serve_requests_total{outcome="error"}``), and each
  scrape optionally appends a snapshot record to a JSONL so ``report
  timeseries`` can render the incident's timeline after the fact.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Any, Callable

from nanodiloco_tpu.obs.telemetry import (
    _fmt,
    _render_labels,
    nearest_rank_percentile,
    render_exposition,
)

# -- the exposition parser (the consumer half of render_exposition) ----------


def _unescape_label_value(s: str) -> str:
    """Invert ``escape_label_value`` in ONE pass. Sequential
    ``.replace`` calls are wrong here: a literal backslash followed by
    the letter n escapes to ``\\\\n`` (three backslash-ish chars), and
    replacing ``\\n`` first would turn the tail of it into a newline."""
    out: list[str] = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            elif nxt == "r":
                # the renderer's CR extension (escape_label_value): a
                # raw CR would tear the line-oriented format, so it
                # travels escaped and is restored here
                out.append("\r")
            else:  # unknown escape: keep verbatim (tolerant)
                out.append(c)
                out.append(nxt)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _unescape_help(s: str) -> str:
    """Invert ``_escape_help`` (backslash, newline, and the CR
    extension)."""
    out: list[str] = []
    i = 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            if s[i + 1] == "\\":
                out.append("\\")
                i += 2
                continue
            if s[i + 1] == "n":
                out.append("\n")
                i += 2
                continue
            if s[i + 1] == "r":
                out.append("\r")
                i += 2
                continue
        out.append(s[i])
        i += 1
    return "".join(out)


def _parse_labels(s: str) -> tuple[dict[str, str], str]:
    """Parse ``{k="v",...}`` at the head of ``s`` with a real scanner
    (escaped quotes and backslashes inside values; a naive split on
    ``","``/``"="`` corrupts both). Returns ``(labels, rest)`` where
    ``rest`` is everything after the closing brace."""
    assert s[0] == "{"
    labels: dict[str, str] = {}
    i = 1
    while i < len(s) and s[i] != "}":
        j = s.index("=", i)
        name = s[i:j].strip().lstrip(",").strip()
        i = j + 1
        if i >= len(s) or s[i] != '"':
            raise ValueError(f"label {name!r} value is not quoted")
        i += 1
        raw: list[str] = []
        while i < len(s):
            if s[i] == "\\" and i + 1 < len(s):
                raw.append(s[i:i + 2])
                i += 2
                continue
            if s[i] == '"':
                break
            raw.append(s[i])
            i += 1
        if i >= len(s):
            raise ValueError("unterminated label value")
        labels[name] = _unescape_label_value("".join(raw))
        i += 1  # past the closing quote
        if i < len(s) and s[i] == ",":
            i += 1
    if i >= len(s):
        raise ValueError("unterminated label set")
    return labels, s[i + 1:]


def _split_exemplar(rest: str) -> tuple[str, tuple[dict, float] | None]:
    """Split an OpenMetrics exemplar suffix (`` # {labels} value``) off
    the text FOLLOWING a sample's label set (safe: label values were
    already consumed, so a ``#`` here cannot be inside a quoted
    string). Returns ``(value_text, exemplar_or_None)`` with exemplar
    as ``(labels, value)``. A malformed suffix is kept in the value
    text untouched — tolerance belongs to the caller's float()."""
    cut = rest.find(" # ")
    if cut < 0:
        return rest, None
    head, tail = rest[:cut], rest[cut + 3:].strip()
    if not tail.startswith("{"):
        return rest, None
    try:
        ex_labels, ex_rest = _parse_labels(tail)
        parts = ex_rest.strip().split()
        if not parts:
            return rest, None
        return head, (ex_labels, float(parts[0]))
    except (ValueError, IndexError):
        return rest, None


def parse_sample_line_ex(
    line: str,
) -> tuple[str, dict[str, str] | None, float, tuple[dict, float] | None]:
    """One exposition sample line -> ``(sample_name, labels, value,
    exemplar)`` where ``exemplar`` is ``(labels, value)`` from an
    OpenMetrics `` # {...} v`` suffix, or None. Raises ValueError on
    anything that is not a sample (comments, blanks, junk) — callers
    decide how tolerant to be."""
    line = line.strip()
    if not line or line.startswith("#"):
        raise ValueError("not a sample line")
    brace = line.find("{")
    if brace >= 0:
        name = line[:brace]
        labels, rest = _parse_labels(line[brace:])
        rest, ex = _split_exemplar(rest)
        parts = rest.strip().split()
        if not parts:  # truncated line: ValueError, never IndexError —
            # scrape_once's per-target isolation catches ValueError
            raise ValueError(f"no value on sample line: {line!r}")
        return name, labels, float(parts[0]), ex
    parts = line.split()
    if len(parts) < 2:
        raise ValueError(f"no value on sample line: {line!r}")
    return parts[0], None, float(parts[1]), None


def parse_sample_line(line: str) -> tuple[str, dict[str, str] | None, float]:
    """One exposition sample line -> ``(sample_name, labels, value)``,
    exemplar-tolerant (an OpenMetrics `` # {...} v`` suffix is parsed
    and dropped). Raises ValueError on non-sample lines."""
    name, labels, value, _ex = parse_sample_line_ex(line)
    return name, labels, value


def sample_key(name: str, labels: dict[str, str] | None) -> str:
    """The canonical flat key for one sample — EXACTLY the text
    ``render_exposition`` emits for it (label order preserved, values
    escaped), so keys survive a parse->flatten->compare round trip."""
    if labels:
        return f"{name}{{{_render_labels(labels)}}}"
    return name


def parse_exposition(text: str) -> list:
    """Parse an OpenMetrics exposition into the SAME ``families``
    structure ``render_exposition`` consumes: ``(name, type, help,
    samples)`` with gauge/counter samples as ``[(labels_or_None,
    value)]`` and histogram samples as ``[(labels_or_None,
    {"buckets": [...], "count": n, "sum": s})]``.

    Strict about this repo's dialect (it must round-trip byte-for-byte:
    ``render_exposition(parse_exposition(t)) == t``), tolerant about
    the rest: unknown comment lines are skipped, samples arriving
    before any ``# TYPE`` get an implicit untyped(gauge) family."""
    families: list = []
    meta: dict[str, tuple[str | None, str | None]] = {}  # name -> (help, type)
    order: list[str] = []
    raw: dict[str, list[tuple[str, dict | None, float]]] = {}

    def ensure(name: str) -> None:
        if name not in meta:
            meta[name] = (None, None)
            order.append(name)
            raw[name] = []

    current: str | None = None
    for line in text.split("\n"):
        stripped = line.strip()
        if not stripped:
            continue
        if stripped.startswith("#"):
            parts = stripped.split(" ", 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                name = parts[2]
                ensure(name)
                h, t = meta[name]
                if parts[1] == "HELP":
                    h = _unescape_help(parts[3]) if len(parts) > 3 else ""
                else:
                    t = parts[3] if len(parts) > 3 else "untyped"
                meta[name] = (h, t)
                current = name
            continue  # EOF marker and foreign comments
        try:
            sname, labels, value, ex = parse_sample_line_ex(stripped)
        except ValueError:
            continue  # tolerant of junk lines in foreign expositions
        owner = None
        if current is not None:
            _, mtype = meta[current]
            suffixes = {
                "counter": ("_total",),
                "histogram": ("_bucket", "_count", "_sum"),
            }.get(mtype or "", ("",))
            if sname == current or any(
                sname == current + sfx for sfx in suffixes
            ):
                owner = current
        if owner is None:
            owner = sname
            ensure(owner)
        raw[owner].append((sname, labels, value, ex))

    for name in order:
        help_text, mtype = meta[name]
        samples = raw[name]
        if mtype == "histogram":
            series: dict[tuple, dict] = {}  # label-sig (minus le) -> snap
            sig_labels: dict[tuple, dict | None] = {}
            for sname, labels, value, ex in samples:
                rest = dict(labels or {})
                le = rest.pop("le", None)
                sig = tuple(sorted(rest.items()))
                if sig not in series:
                    series[sig] = {"buckets": [], "count": 0, "sum": 0.0}
                    sig_labels[sig] = rest or None
                snap = series[sig]
                if sname == name + "_bucket":
                    if le is None:  # foreign bucket without an le
                        # label: skip the sample, never crash the
                        # scrape (float(None) is a TypeError that
                        # would escape the per-target isolation)
                        continue
                    bound = le if le == "+Inf" else float(le)
                    snap["buckets"].append((bound, int(value)))
                    if ex is not None and "trace_id" in ex[0]:
                        # rebuild the snapshot's exemplars map so the
                        # byte round-trip holds with exemplars present
                        snap.setdefault("exemplars", {})[bound] = (
                            ex[0]["trace_id"], ex[1]
                        )
                elif sname == name + "_count":
                    snap["count"] = int(value)
                elif sname == name + "_sum":
                    snap["sum"] = float(value)
            fam_samples = [(sig_labels[sig], series[sig]) for sig in series]
        elif mtype == "counter":
            fam_samples = [
                (labels, value) for _sname, labels, value, _ex in samples
            ]
        else:
            fam_samples = [
                (labels, value) for _sname, labels, value, _ex in samples
            ]
        families.append((name, mtype or "untyped", help_text, fam_samples))
    return families


def flatten_families(families: list) -> dict[str, float]:
    """Families -> one flat ``{sample_key: value}`` dict, keys exactly
    as rendered (``name_total{label="v"}``), histograms expanded to
    their ``_bucket``/``_count``/``_sum`` samples — the shape the
    series store ingests."""
    out: dict[str, float] = {}
    for name, mtype, _help, samples in families:
        if mtype == "histogram":
            series = (
                [(None, samples)] if isinstance(samples, dict) else samples
            )
            for labels, snap in series:
                for le, cum in snap["buckets"]:
                    # telemetry's _fmt, not a local copy: the key/render
                    # byte parity depends on ONE formatting rule
                    le_s = le if isinstance(le, str) else _fmt(float(le))
                    bl = dict(labels or {})
                    bl["le"] = le_s
                    out[sample_key(name + "_bucket", bl)] = float(cum)
                out[sample_key(name + "_count", labels)] = float(snap["count"])
                out[sample_key(name + "_sum", labels)] = float(snap["sum"])
            continue
        sname = name + "_total" if mtype == "counter" else name
        for labels, value in samples:
            out[sample_key(sname, labels)] = float(value)
    return out


# -- the time-series store ----------------------------------------------------


class SeriesStore:
    """Bounded per-series ring buffers of ``(t, value)`` samples.
    ``maxlen`` bounds EVERY series (oldest samples evicted); all reads
    and writes are lock-guarded — the scrape loop appends while the SLO
    evaluator and HTTP threads query."""

    def __init__(self, maxlen: int = 2048, *,
                 long_bucket_s: float = 60.0,
                 long_maxlen: int = 1024) -> None:
        if maxlen < 1:
            raise ValueError(f"maxlen must be >= 1; got {maxlen}")
        if long_bucket_s <= 0:
            raise ValueError(
                f"long_bucket_s must be > 0; got {long_bucket_s}"
            )
        if long_maxlen < 1:
            raise ValueError(f"long_maxlen must be >= 1; got {long_maxlen}")
        self.maxlen = int(maxlen)
        # long-horizon retention tier BEHIND the ring buffers: every
        # sample also lands in a time-bucketed downsample (one point
        # per ``long_bucket_s``, the bucket's LAST value — the same
        # convention a counter scrape keeps), bounded by
        # ``long_maxlen`` buckets. At the defaults that is ~17 hours
        # of per-minute trend per series behind a ~2048-sample ring —
        # the forecaster and the offline dashboard keep multi-hour
        # history without unbounded memory.
        self.long_bucket_s = float(long_bucket_s)
        self.long_maxlen = int(long_maxlen)
        self._series: dict[str, collections.deque] = {}
        # key -> (closed-bucket deque of (bucket_start_t, last_value),
        #         open bucket id or None, open bucket's last value)
        self._long: dict[str, collections.deque] = {}
        self._long_open: dict[str, tuple[int, float]] = {}
        self._lock = threading.Lock()

    def add(self, key: str, t: float, value: float) -> None:
        t = float(t)
        value = float(value)
        with self._lock:
            dq = self._series.get(key)
            if dq is None:
                dq = self._series[key] = collections.deque(maxlen=self.maxlen)
            dq.append((t, value))
            # feed the long tier: flush the open bucket when this
            # sample starts a later one (out-of-order samples within a
            # flushed bucket are rare and simply start a new bucket)
            bucket = int(t // self.long_bucket_s)
            open_ = self._long_open.get(key)
            if open_ is not None and open_[0] != bucket:
                ldq = self._long.get(key)
                if ldq is None:
                    ldq = self._long[key] = collections.deque(
                        maxlen=self.long_maxlen
                    )
                ldq.append((open_[0] * self.long_bucket_s, open_[1]))
            self._long_open[key] = (bucket, value)

    def long_window(self, key: str, since: float,
                    until: float | None = None) -> list[tuple[float, float]]:
        """Downsampled long-horizon samples (one per bucket, the
        bucket's last value, stamped at the bucket start), oldest
        first; the still-open bucket is included, stamped at its own
        bucket start. The dashboard's multi-hour trend source."""
        with self._lock:
            samples = list(self._long.get(key, ()))
            open_ = self._long_open.get(key)
            if open_ is not None:
                samples.append(
                    (open_[0] * self.long_bucket_s, open_[1])
                )
        return [
            (t, v) for t, v in samples
            if t >= since and (until is None or t <= until)
        ]

    def long_snapshot(self) -> dict[str, list[tuple[float, float]]]:
        """Every series' long-tier samples (open bucket included)."""
        with self._lock:
            keys = set(self._long) | set(self._long_open)
        return {k: self.long_window(k, float("-inf")) for k in sorted(keys)}

    def keys(self, contains: str | None = None) -> list[str]:
        with self._lock:
            ks = list(self._series)
        if contains:
            ks = [k for k in ks if contains in k]
        return sorted(ks)

    def latest(self, key: str) -> tuple[float, float] | None:
        with self._lock:
            dq = self._series.get(key)
            return dq[-1] if dq else None

    def window(self, key: str, since: float,
               until: float | None = None) -> list[tuple[float, float]]:
        """Samples with ``since <= t`` (and ``t <= until`` when given),
        oldest first."""
        with self._lock:
            dq = self._series.get(key)
            if not dq:
                return []
            samples = list(dq)
        return [
            (t, v) for t, v in samples
            if t >= since and (until is None or t <= until)
        ]

    def agg(self, key: str, window_s: float, now: float,
            fn: str = "mean") -> float | None:
        """Windowed aggregate over the last ``window_s`` seconds:
        ``mean``/``max``/``min``/``last``; None with no samples."""
        vals = [v for _, v in self.window(key, now - window_s, now)]
        if not vals:
            return None
        if fn == "mean":
            return sum(vals) / len(vals)
        if fn == "max":
            return max(vals)
        if fn == "min":
            return min(vals)
        if fn == "last":
            return vals[-1]
        raise ValueError(f"unknown aggregate {fn!r}")

    def percentile(self, key: str, p: float, window_s: float,
                   now: float) -> float | None:
        """Nearest-rank percentile of the windowed samples (the same
        definition every other percentile in this repo uses)."""
        vals = sorted(v for _, v in self.window(key, now - window_s, now))
        return nearest_rank_percentile(vals, p)

    def increase(self, key: str, window_s: float,
                 now: float) -> float | None:
        """Counter increase over the window: the sum of POSITIVE
        deltas, so a process restart (the counter drops to 0) reads as
        a reset rather than a huge negative rate. None with fewer than
        two samples in the window."""
        samples = self.window(key, now - window_s, now)
        if len(samples) < 2:
            return None
        inc = 0.0
        for (_, a), (_, b) in zip(samples, samples[1:]):
            if b > a:
                inc += b - a
        return inc

    def rate(self, key: str, window_s: float, now: float) -> float | None:
        """Per-second counter rate over the window (increase / elapsed
        between the first and last windowed samples)."""
        samples = self.window(key, now - window_s, now)
        if len(samples) < 2:
            return None
        elapsed = samples[-1][0] - samples[0][0]
        if elapsed <= 0:
            return None
        inc = self.increase(key, window_s, now)
        return None if inc is None else inc / elapsed

    # Theil-Sen is O(n^2) in pair count; windows are resampled down to
    # this many points first (evenly strided, newest kept) so a maxed-out
    # ring buffer cannot turn one autoscaler tick into ~2M pair slopes
    _SLOPE_MAX_POINTS = 48

    def slope(self, key: str, window_s: float, now: float,
              *, counter: bool = False) -> float | None:
        """Robust per-second trend over the window: the Theil-Sen
        estimator (median of all pairwise slopes), so one garbage sample
        — a scrape racing a restart, a transient spike — cannot swing
        the estimate the way least-squares would, and the autoscaler
        never acts on a phantom trend.

        With ``counter=True`` the samples are first folded into a
        monotone cumulative series using the same positive-deltas-only
        rule as ``increase()``: a process restart (counter drops toward
        0) reads as a reset, not a cliff of negative slope. None with
        fewer than two samples or no elapsed time."""
        samples = self.window(key, now - window_s, now)
        if len(samples) < 2:
            return None
        if samples[-1][0] - samples[0][0] <= 0:
            return None
        if counter:
            folded: list[tuple[float, float]] = [(samples[0][0], 0.0)]
            cum = 0.0
            for (_, a), (t, b) in zip(samples, samples[1:]):
                if b > a:
                    cum += b - a
                folded.append((t, cum))
            samples = folded
        if len(samples) > self._SLOPE_MAX_POINTS:
            stride = len(samples) / self._SLOPE_MAX_POINTS
            samples = [
                samples[min(len(samples) - 1, int(i * stride))]
                for i in range(self._SLOPE_MAX_POINTS - 1)
            ] + [samples[-1]]
        slopes: list[float] = []
        for i in range(len(samples)):
            t0, v0 = samples[i]
            for t1, v1 in samples[i + 1:]:
                if t1 > t0:
                    slopes.append((v1 - v0) / (t1 - t0))
        if not slopes:
            return None
        slopes.sort()
        mid = len(slopes) // 2
        if len(slopes) % 2:
            return slopes[mid]
        return (slopes[mid - 1] + slopes[mid]) / 2.0

    def forecast_exhaustion(self, key: str, bound: float, window_s: float,
                            now: float, *,
                            kind: str = "floor") -> float | None:
        """Seconds until the series crosses ``bound`` at its current
        ``slope()`` — the question "when does ``kv_blocks_free`` hit 0"
        or "when does queue depth hit slot capacity", asked of the
        trend rather than the point gauge. ``kind="floor"`` forecasts a
        falling series crossing down through the bound; ``"ceiling"`` a
        rising series crossing up. Returns 0.0 when the latest sample
        is already past the bound, None when the series is trending
        away from it (or has no usable trend)."""
        if kind not in ("floor", "ceiling"):
            raise ValueError(f"kind must be 'floor' or 'ceiling'; got "
                             f"{kind!r}")
        last = self.latest(key)
        if last is None:
            return None
        _, v = last
        if kind == "floor" and v <= bound:
            return 0.0
        if kind == "ceiling" and v >= bound:
            return 0.0
        s = self.slope(key, window_s, now)
        if s is None:
            return None
        if kind == "floor":
            return (bound - v) / s if s < 0 else None
        return (bound - v) / s if s > 0 else None

    def snapshot(self) -> dict[str, list[tuple[float, float]]]:
        with self._lock:
            return {k: list(dq) for k, dq in self._series.items()}


# -- the scrape loop ----------------------------------------------------------


def _default_fetch(url: str, timeout: float) -> str:
    from nanodiloco_tpu.serve.client import http_get

    code, body = http_get(url, timeout=timeout)
    if code != 200:
        raise OSError(f"scrape answered {code}")
    return body


class Collector:
    """Poll each target's ``/metrics`` on a cadence into a SeriesStore.

    ``targets`` is ``[(name, base_url)]``; the series key is
    ``name:sample`` with the sample's label text verbatim. ``fetch``,
    ``clock`` (monotonic — the store's timebase), ``wall``, and
    ``sleep`` are injectable so every scrape decision is testable with
    a scripted fleet and a fake clock. When ``series_jsonl`` is set,
    each scrape appends one ``{"series": target, "t_unix", "t",
    "samples": {...}}`` record per reachable target — the artifact
    ``report timeseries`` renders after the incident."""

    def __init__(
        self,
        targets: list[tuple[str, str]],
        *,
        interval_s: float = 1.0,
        timeout_s: float = 5.0,
        fetch: Callable[[str, float], str] | None = None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
        store: SeriesStore | None = None,
        maxlen: int = 2048,
        series_jsonl: str | None = None,
    ) -> None:
        if not targets:
            raise ValueError("a collector needs at least one target")
        names = [n for n, _ in targets]
        if len(set(names)) != len(names):
            raise ValueError(f"target names must be unique; got {names}")
        self.targets = [(str(n), str(u).rstrip("/")) for n, u in targets]
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._fetch = fetch or _default_fetch
        self._clock = clock
        self._wall = wall
        self._sleep = sleep
        self.store = store or SeriesStore(maxlen=maxlen)
        self.series_jsonl = series_jsonl
        self._jsonl_lock = threading.Lock()
        self.scrapes = 0
        self.scrape_errors: dict[str, int] = {}
        self.last_scrape_t: float | None = None

    def key(self, target: str, sample: str) -> str:
        return f"{target}:{sample}"

    def set_targets(self, targets: list[tuple[str, str]]) -> None:
        """Replace the target set (the fleet autoscaler follows elastic
        membership with this: launched replicas start being scraped,
        retired ones stop). Series already collected for a departed
        target stay in the store — history must survive the replica."""
        if not targets:
            raise ValueError("a collector needs at least one target")
        names = [n for n, _ in targets]
        if len(set(names)) != len(names):
            raise ValueError(f"target names must be unique; got {names}")
        self.targets = [(str(n), str(u).rstrip("/")) for n, u in targets]

    def scrape_once(self) -> dict[str, Any]:
        """One sweep over every target: fetch, parse, store. Returns
        ``{target: sample_count | {"error": ...}}`` — a failed target
        never aborts the sweep (an unreachable replica is exactly when
        the rest of the fleet's series matter most)."""
        now = self._clock()
        out: dict[str, Any] = {}
        for name, url in self.targets:
            try:
                text = self._fetch(url + "/metrics", self.timeout_s)
                samples = flatten_families(parse_exposition(text))
            except (OSError, ValueError) as e:
                self.scrape_errors[name] = self.scrape_errors.get(name, 0) + 1
                out[name] = {"error": f"{type(e).__name__}: {e}"}
                continue
            for sample, value in samples.items():
                if math.isnan(value):
                    continue  # a NaN sample poisons every window query
                self.store.add(self.key(name, sample), now, value)
            out[name] = len(samples)
            self._append_snapshot(name, now, samples)
        self.scrapes += 1
        self.last_scrape_t = now
        return out

    def _append_snapshot(self, target: str, t: float,
                         samples: dict[str, float]) -> None:
        if not self.series_jsonl:
            return
        rec = {
            "series": target,
            "t_unix": round(self._wall(), 3),
            "t": round(t, 6),
            "samples": samples,
        }
        try:
            d = os.path.dirname(os.path.abspath(self.series_jsonl))
            os.makedirs(d, exist_ok=True)
            with self._jsonl_lock, open(self.series_jsonl, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass  # a full disk must not take down collection

    def run(self, stop: threading.Event | None = None,
            max_scrapes: int | None = None,
            on_scrape: Callable[[dict], None] | None = None) -> None:
        """Scrape until ``stop`` is set (or ``max_scrapes`` exhausted).
        ``on_scrape`` runs after every sweep — the SLO monitor's
        evaluate hook rides here, so collection and evaluation share
        one cadence."""
        n = 0
        while stop is None or not stop.is_set():
            result = self.scrape_once()
            if on_scrape is not None:
                on_scrape(result)
            n += 1
            if max_scrapes is not None and n >= max_scrapes:
                return
            if stop is not None:
                stop.wait(self.interval_s)
            else:
                self._sleep(self.interval_s)

    def render_metrics(self) -> str:
        """The collector's OWN exposition (the obs-watch endpoint):
        scrape counters and per-target error counts — the watcher is
        itself watchable."""
        families: list = [
            ("nanodiloco_obs_scrapes", "counter",
             "collector scrape sweeps completed", [(None, self.scrapes)]),
            ("nanodiloco_obs_series", "gauge",
             "distinct series held in the ring-buffer store",
             [(None, len(self.store.keys()))]),
        ]
        if self.scrape_errors:
            families.append((
                "nanodiloco_obs_scrape_errors", "counter",
                "failed scrape attempts by target",
                [({"target": t}, n)
                 for t, n in sorted(self.scrape_errors.items())]
                + [(None, sum(self.scrape_errors.values()))],
            ))
        return render_exposition(families)


# -- after-the-fact timeline (report timeseries) ------------------------------

SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float], width: int = 60) -> str:
    """ASCII(-ish) sparkline of a series, resampled to ``width`` points
    (stride sampling keeps the newest point). Flat series render as a
    mid-level bar, not a crash into the bottom glyph."""
    if not values:
        return ""
    width = max(1, int(width))  # --width 0 must not divide by zero
    if len(values) > width:
        stride = len(values) / width
        values = [values[min(len(values) - 1, int(i * stride))]
                  for i in range(width - 1)] + [values[-1]]
    lo, hi = min(values), max(values)
    if hi <= lo:
        return SPARK_CHARS[3] * len(values)
    span = hi - lo
    return "".join(
        SPARK_CHARS[min(len(SPARK_CHARS) - 1,
                        int((v - lo) / span * len(SPARK_CHARS)))]
        for v in values
    )


def read_series_jsonl(path: str) -> dict[str, list[tuple[float, float]]]:
    """Collector snapshot JSONL -> ``{target:sample: [(t_unix, v)]}``,
    torn trailing lines tolerated (the collector may still be
    appending)."""
    from nanodiloco_tpu.training.metrics import read_jsonl_records

    recs, _torn = read_jsonl_records(path)
    out: dict[str, list[tuple[float, float]]] = {}
    for r in recs:
        target = r.get("series")
        samples = r.get("samples")
        t = r.get("t_unix", r.get("t"))
        if not target or not isinstance(samples, dict) or t is None:
            continue
        for sample, value in samples.items():
            if isinstance(value, (int, float)):
                out.setdefault(f"{target}:{sample}", []).append(
                    (float(t), float(value))
                )
    return out
