"""Declarative SLOs with multi-window burn-rate alerting.

The serving quantities worth alerting on are exactly the vLLM-lineage
ones this repo already exposes as gauges (arXiv:2309.06180): TTFT
percentiles, client-visible decode tokens/s, KV-block headroom — plus
the fleet's own goodput fraction and the trainer's outer staleness.
The alerting discipline is the classic fast+slow MULTI-WINDOW burn
rate: a FAST window trips quickly on a real incident (minutes of
latency budget burning now) and a SLOW window confirms it is not a
blip, so a single bad scrape never pages and a sustained burn always
does. Recovery is debounced: the fast window must stay clean for
``clear_debounce_s`` before an alert resolves, so a flapping signal
emits one firing/resolved pair, not a storm.

Each rule names a series in the collector's store (``obs/collector``),
a bound, and a direction (``ceiling``: bad above; ``floor``: bad
below). The burn fraction of a window is the fraction of its samples
in breach (for the derived error-rate rule: whether the windowed
error/total counter-increase ratio breaches). A rule fires for a
target when BOTH windows exceed their burn thresholds.

Breaches emit ``slo_alert`` JSONL records — the same schema family as
the watchdog's alarm records, so they flow into ``report faults``,
``summarize_run`` (``slo_alerts_total`` / ``slo_worst_rule`` /
``slo_burn_seconds``), and the ``nanodiloco_slo_alerts_total{rule}``
counter family — and call an action hook the fleet wires up: the
router marks a burning replica not-preferred (route-around BEFORE any
503-ejection; the replica is slow, not dead) and the
``DeployController`` refuses to start a canary while a fleet-scope
rule burns (deploying into an incident is how incidents compound).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable

from nanodiloco_tpu.obs.collector import SeriesStore
from nanodiloco_tpu.obs.telemetry import render_exposition


@dataclasses.dataclass(frozen=True)
class SLORule:
    """One declarative SLO. ``key`` is the collector sample key WITHOUT
    the target prefix (the rule is evaluated per target that exposes
    it). ``scope`` says what the action hook should do about a breach:
    ``replica`` rules route around the burning target; ``fleet`` rules
    gate deployment. ``derive="error_rate"`` ignores ``key`` and
    computes the windowed error/total ratio from the
    ``requests_by_outcome`` counter family instead."""

    name: str
    key: str
    bound: float
    kind: str = "ceiling"            # "ceiling" | "floor"
    scope: str = "replica"           # "replica" | "fleet"
    fast_window_s: float = 5.0
    slow_window_s: float = 30.0
    fast_burn: float = 0.5           # breach fraction tripping the fast window
    slow_burn: float = 0.25          # breach fraction confirming over the slow
    clear_debounce_s: float = 5.0
    derive: str | None = None        # None | "error_rate"

    def __post_init__(self) -> None:
        if self.kind not in ("ceiling", "floor"):
            raise ValueError(f"kind must be ceiling|floor; got {self.kind!r}")
        if self.scope not in ("replica", "fleet"):
            raise ValueError(f"scope must be replica|fleet; got {self.scope!r}")
        if self.fast_window_s <= 0 or self.slow_window_s < self.fast_window_s:
            raise ValueError(
                "windows must satisfy 0 < fast_window_s <= slow_window_s; "
                f"got {self.fast_window_s}/{self.slow_window_s}"
            )
        if not 0.0 < self.fast_burn <= 1.0 or not 0.0 < self.slow_burn <= 1.0:
            raise ValueError("burn thresholds must be in (0, 1]")

    def breached(self, value: float) -> bool:
        return value > self.bound if self.kind == "ceiling" \
            else value < self.bound


# series keys as the serve/router /metrics endpoints expose them
TTFT_P95_KEY = "nanodiloco_serve_ttft_p95_seconds"
# the protected class's latency under class-aware shedding: the gauge
# the fleet-wide p95 cannot substitute for (it mixes the protected
# class with the best-effort classes being sacrificed)
CLASS0_TTFT_P95_KEY = 'nanodiloco_serve_class_ttft_p95_seconds{priority="0"}'
DECODE_TPS_KEY = "nanodiloco_serve_decode_tokens_per_sec"
KV_FREE_KEY = "nanodiloco_kv_blocks_free"
FLEET_GOODPUT_KEY = "nanodiloco_fleet_goodput_fraction"
OUTER_STALENESS_KEY = "nanodiloco_outer_staleness"
REQUESTS_ERROR_KEY = 'nanodiloco_serve_requests_total{outcome="error"}'
REQUESTS_TOTAL_KEY = "nanodiloco_serve_requests_total"


def standard_rules(
    *,
    ttft_p95_max_s: float | None = None,
    class0_ttft_p95_max_s: float | None = None,
    decode_tps_min: float | None = None,
    error_rate_max: float | None = None,
    kv_blocks_free_min: float | None = None,
    fleet_goodput_min: float | None = None,
    outer_staleness_max: float | None = None,
    fast_window_s: float = 5.0,
    slow_window_s: float = 30.0,
    fast_burn: float = 0.5,
    slow_burn: float = 0.25,
    clear_debounce_s: float = 5.0,
) -> list[SLORule]:
    """The repo's standard SLO set; a None threshold omits its rule.
    Rule names are stable identifiers (they key the alert counter
    family and the compare summary)."""
    win = dict(fast_window_s=fast_window_s, slow_window_s=slow_window_s,
               fast_burn=fast_burn, slow_burn=slow_burn,
               clear_debounce_s=clear_debounce_s)
    rules: list[SLORule] = []
    if ttft_p95_max_s is not None:
        rules.append(SLORule("short_ttft_p95_s", TTFT_P95_KEY,
                             ttft_p95_max_s, "ceiling", "replica", **win))
    if class0_ttft_p95_max_s is not None:
        # the class-aware shedding contract: while lower classes shed,
        # THIS rule is the one that must stay quiet
        rules.append(SLORule("class0_ttft_p95_s", CLASS0_TTFT_P95_KEY,
                             class0_ttft_p95_max_s, "ceiling", "replica",
                             **win))
    if decode_tps_min is not None:
        rules.append(SLORule("decode_tokens_per_sec", DECODE_TPS_KEY,
                             decode_tps_min, "floor", "replica", **win))
    if error_rate_max is not None:
        rules.append(SLORule("error_rate", REQUESTS_TOTAL_KEY,
                             error_rate_max, "ceiling", "replica",
                             derive="error_rate", **win))
    if kv_blocks_free_min is not None:
        rules.append(SLORule("kv_blocks_free", KV_FREE_KEY,
                             kv_blocks_free_min, "floor", "replica", **win))
    if fleet_goodput_min is not None:
        rules.append(SLORule("fleet_goodput_fraction", FLEET_GOODPUT_KEY,
                             fleet_goodput_min, "floor", "fleet", **win))
    if outer_staleness_max is not None:
        rules.append(SLORule("outer_staleness", OUTER_STALENESS_KEY,
                             outer_staleness_max, "ceiling", "fleet", **win))
    return rules


class _AlertState:
    """Per (rule, target) state machine: ok -> firing -> (debounced)
    resolved. Burn seconds accumulate while firing — the compare-gated
    incident cost."""

    def __init__(self) -> None:
        self.firing = False
        self.fired_at: float | None = None
        self.clean_since: float | None = None
        self.burn_s = 0.0
        self.last_eval_t: float | None = None


class SLOMonitor:
    """Evaluate ``rules`` over a collector's ``SeriesStore``.

    ``targets`` are the collector's target names; each rule is
    evaluated against every target whose store carries its series (a
    fleet-goodput rule only matches the router target, TTFT rules only
    the replicas — no manual wiring). ``on_alert(rule, target,
    firing)`` is the action hook; a hook failure is counted, never
    fatal (alert evaluation must survive a dead router). ``clock`` is
    the store's timebase (monotonic); ``wall`` stamps the JSONL."""

    def __init__(
        self,
        store: SeriesStore,
        rules: list[SLORule],
        targets: list[str],
        *,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        alerts_jsonl: str | None = None,
        on_alert: Callable[[SLORule, str, bool], None] | None = None,
        quiet: bool = True,
    ) -> None:
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"rule names must be unique; got {names}")
        self.store = store
        self.rules = list(rules)
        self.targets = list(targets)
        self._clock = clock
        self._wall = wall
        self.alerts_jsonl = alerts_jsonl
        self._on_alert = on_alert
        self.quiet = quiet
        self._lock = threading.Lock()
        self._jsonl_lock = threading.Lock()
        self._states: dict[tuple[str, str], _AlertState] = {}
        self.alerts_fired: dict[str, int] = {}   # rule -> firing transitions
        self.hook_errors = 0
        # transitions whose hook call FAILED (router booting, transient
        # socket error): retried with the pair's CURRENT state on every
        # evaluate until one lands — a route-around lost to a refused
        # connection would otherwise never happen at all
        self._hook_pending: set[tuple[str, str]] = set()

    # -- burn math -----------------------------------------------------------

    def _series_key(self, target: str, sample: str) -> str:
        return f"{target}:{sample}"

    def burn_fraction(self, rule: SLORule, target: str, window_s: float,
                      now: float) -> float | None:
        """Fraction of the window in breach: per-sample for plain
        series; for the derived error rate, 1.0/0.0 on whether the
        windowed increase ratio breaches (a ratio has no per-sample
        form). None when the window holds no evidence — absence never
        TRIPS an alert (firing needs both windows on real samples);
        for an already-firing alert, sustained absence counts as clean
        and resolves after the debounce (see _evaluate_one)."""
        if rule.derive == "error_rate":
            total = self.store.increase(
                self._series_key(target, REQUESTS_TOTAL_KEY), window_s, now
            )
            if not total:
                return None
            errors = self.store.increase(
                self._series_key(target, REQUESTS_ERROR_KEY), window_s, now
            ) or 0.0
            return 1.0 if (errors / total) > rule.bound else 0.0
        samples = self.store.window(
            self._series_key(target, rule.key), now - window_s, now
        )
        if not samples:
            return None
        bad = sum(1 for _, v in samples if rule.breached(v))
        return bad / len(samples)

    def _matches(self, rule: SLORule, target: str) -> bool:
        key = REQUESTS_TOTAL_KEY if rule.derive == "error_rate" else rule.key
        return self.store.latest(self._series_key(target, key)) is not None

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One evaluation sweep; returns the alert records EMITTED this
        sweep (firing and resolved transitions only — steady states are
        silent, burn seconds still accumulate)."""
        now = self._clock() if now is None else now
        emitted: list[dict] = []
        for rule in self.rules:
            for target in self.targets:
                if not self._matches(rule, target):
                    continue
                rec = self._evaluate_one(rule, target, now)
                if rec is not None:
                    emitted.append(rec)
        self._retry_pending_hooks()
        return emitted

    def _retry_pending_hooks(self) -> None:
        for rule_name, target in sorted(self._hook_pending):
            rule = next((r for r in self.rules if r.name == rule_name),
                        None)
            if rule is None:
                self._hook_pending.discard((rule_name, target))
                continue
            with self._lock:
                st = self._states.get((rule_name, target))
                firing = bool(st is not None and st.firing)
            # the CURRENT state, not the state at failure time: if the
            # alert resolved while the router was unreachable, the
            # retry must deliver the clear, never a stale burn
            self._call_hook(rule, target, firing)

    def _evaluate_one(self, rule: SLORule, target: str,
                      now: float) -> dict | None:
        fast = self.burn_fraction(rule, target, rule.fast_window_s, now)
        slow = self.burn_fraction(rule, target, rule.slow_window_s, now)
        transition: str | None = None
        # decide under the lock, EMIT outside it: _emit runs the action
        # hook (an HTTP POST to the router, seconds under a timeout),
        # and holding the lock across it would stall the watcher's own
        # /metrics endpoint exactly during the incident it reports
        with self._lock:
            st = self._states.setdefault((rule.name, target), _AlertState())
            if st.firing and st.last_eval_t is not None and fast is not None:
                # burn accrues only while there is EVIDENCE: a series
                # that vanished (route-around starved the error-rate
                # counters of traffic) must not inflate the
                # compare-gated burn seconds from silence
                st.burn_s += max(0.0, now - st.last_eval_t)
            st.last_eval_t = now
            if not st.firing:
                # fast window trips, slow window confirms — both must
                # burn for the alert to fire (the multi-window AND)
                if (fast is not None and slow is not None
                        and fast >= rule.fast_burn
                        and slow >= rule.slow_burn):
                    st.firing = True
                    st.fired_at = now
                    st.clean_since = None
                    self.alerts_fired[rule.name] = (
                        self.alerts_fired.get(rule.name, 0) + 1
                    )
                    transition = "firing"
            else:
                # firing: resolve only after the fast window stays
                # CLEAN for the debounce period — a flapping burn
                # re-arms the clean timer instead of emitting
                # resolve/fire pairs. NO EVIDENCE counts as clean:
                # the system's own remediation can starve the signal
                # (route-around leaves the error-rate counters flat),
                # and an alert that can never resolve burns forever;
                # re-firing requires both windows to trip on real
                # evidence again, so this cannot mask a live burn
                clean = fast is None or fast == 0.0
                if not clean:
                    st.clean_since = None
                else:
                    if st.clean_since is None:
                        st.clean_since = now
                    if now - st.clean_since >= rule.clear_debounce_s:
                        st.firing = False
                        st.clean_since = None
                        transition = "resolved"
        if transition is None:
            return None
        return self._emit(rule, target, transition, fast, slow, st)

    def _emit(self, rule: SLORule, target: str, state: str,
              fast: float | None, slow: float | None,
              st: _AlertState, **extra) -> dict:
        rec = {
            "slo_alert": rule.name,
            "state": state,
            "target": target,
            "scope": rule.scope,
            "bound": rule.bound,
            "kind": rule.kind,
            "fast_burn": None if fast is None else round(fast, 4),
            "slow_burn": None if slow is None else round(slow, 4),
            "t_unix": round(self._wall(), 3),
            **extra,
        }
        if state != "firing":
            rec["burn_s"] = round(st.burn_s, 3)
        self._append_jsonl(rec)
        self._call_hook(rule, target, state == "firing")
        if not self.quiet:
            print(f"[slo] {json.dumps(rec)}", flush=True)
        return rec

    def _call_hook(self, rule: SLORule, target: str, firing: bool) -> None:
        if self._on_alert is None:
            return
        try:
            self._on_alert(rule, target, firing)
            self._hook_pending.discard((rule.name, target))
        except Exception:
            # a dead router must not kill alerting — count it and queue
            # the pair for retry on the next evaluate
            self.hook_errors += 1
            self._hook_pending.add((rule.name, target))

    def _append_jsonl(self, rec: dict) -> None:
        if not self.alerts_jsonl:
            return
        try:
            d = os.path.dirname(os.path.abspath(self.alerts_jsonl))
            os.makedirs(d, exist_ok=True)
            with self._jsonl_lock, open(self.alerts_jsonl, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass  # a full disk must not take down alerting

    # -- state surface -------------------------------------------------------

    def firing(self) -> list[tuple[str, str]]:
        """Currently-firing ``(rule, target)`` pairs."""
        with self._lock:
            return sorted(k for k, st in self._states.items() if st.firing)

    def fleet_burning(self) -> bool:
        """True while any FLEET-scope rule fires — the deploy
        controller's canary gate."""
        scopes = {r.name: r.scope for r in self.rules}
        return any(scopes.get(rule) == "fleet"
                   for rule, _t in self.firing())

    def burn_seconds(self) -> dict[str, float]:
        """Cumulative firing seconds per rule (all targets summed)."""
        out: dict[str, float] = {}
        with self._lock:
            for (rule, _target), st in self._states.items():
                out[rule] = out.get(rule, 0.0) + st.burn_s
        return {k: round(v, 3) for k, v in out.items()}

    def finalize(self) -> dict:
        """Shutdown: resolve still-firing alerts (reason=shutdown, so
        the JSONL's burn accounting is complete) and append one
        ``slo_summary`` record — the artifact ``summarize_run`` reads
        even when no individual alert ever resolved."""
        now = self._clock()
        with self._lock:
            open_keys = [k for k, st in self._states.items() if st.firing]
        for rule_name, target in open_keys:
            rule = next(r for r in self.rules if r.name == rule_name)
            with self._lock:
                st = self._states[(rule_name, target)]
                if st.last_eval_t is not None:
                    st.burn_s += max(0.0, now - st.last_eval_t)
                    st.last_eval_t = now
                st.firing = False
            self._emit(rule, target, "resolved", None, None, st,
                       reason="shutdown")
        burn = self.burn_seconds()
        summary = {
            "slo_summary": {
                "alerts_total": sum(self.alerts_fired.values()),
                "alerts_by_rule": dict(sorted(self.alerts_fired.items())),
                "burn_seconds_by_rule": burn,
                "burn_seconds_total": round(sum(burn.values()), 3),
                **({"worst_rule": max(burn, key=burn.get)} if burn else {}),
            },
            "t_unix": round(self._wall(), 3),
        }
        self._append_jsonl(summary)
        return summary

    def render_metrics(self) -> str:
        """The monitor's exposition (served by ``obs-watch``):
        ``nanodiloco_slo_alerts_total{rule}``, per-pair burning gauges,
        and cumulative burn seconds."""
        with self._lock:
            firing = sorted(
                (k, st.burn_s) for k, st in self._states.items()
                if st.firing
            )
            # snapshot under the lock: the evaluator inserts a rule's
            # first firing transition concurrently with HTTP scrapes
            # of this endpoint — an unguarded iteration would crash
            # the watcher's own /metrics exactly as an incident starts
            fired = dict(self.alerts_fired)
            hook_errors = self.hook_errors
        burn = self.burn_seconds()
        families: list = [(
            "nanodiloco_slo_alerts", "counter",
            "SLO burn-rate alerts fired, by rule",
            [({"rule": r}, n) for r, n in sorted(fired.items())]
            + [(None, sum(fired.values()))],
        )]
        if firing:
            families.append((
                "nanodiloco_slo_burning", "gauge",
                "1 per (rule, target) currently firing",
                [({"rule": r, "target": t}, 1) for (r, t), _ in firing],
            ))
        if burn:
            families.append((
                "nanodiloco_slo_burn_seconds", "counter",
                "cumulative seconds each rule has spent firing",
                [({"rule": r}, s) for r, s in sorted(burn.items())]
                + [(None, round(sum(burn.values()), 3))],
            ))
        if hook_errors:
            families.append((
                "nanodiloco_slo_hook_errors", "counter",
                "action-hook invocations that raised",
                [(None, hook_errors)],
            ))
        return render_exposition(families)


def router_action_hook(post: Callable[[str, dict], Any],
                       router_url: str) -> Callable[[SLORule, str, bool], None]:
    """The wire form of the action hook: POST each transition to the
    fleet router's ``/fleet/slo`` endpoint (replica-scope -> the router
    marks that replica not-preferred; fleet-scope -> the deploy
    controller's canary gate). ``post`` is ``(url, doc) -> (code,
    body)`` — injectable; the default caller passes
    ``serve/client.http_post_json``."""

    def hook(rule: SLORule, target: str, firing: bool) -> None:
        result = post(router_url.rstrip("/") + "/fleet/slo", {
            "rule": rule.name,
            "scope": rule.scope,
            "target": target,
            "firing": firing,
        })
        # http_post_json returns 4xx/5xx instead of raising: a refused
        # transition (mismatched target name, router mid-restart) must
        # surface as a hook FAILURE — counted, queued for retry — not a
        # silent success that never route-arounds anything
        if isinstance(result, tuple) and result and isinstance(
            result[0], int
        ) and not 200 <= result[0] < 300:
            raise OSError(
                f"/fleet/slo answered {result[0]}: {result[1]}"
            )

    return hook
