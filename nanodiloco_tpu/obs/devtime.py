"""Device-time accounting: every dispatched second attributed to a program.

MegaScale's every-second discipline (arXiv:2402.15627) applied one
level below the wall clock: the serving engine and the training loop
wrap every compiled-program call site — prefill chunk, decode tick,
verify window, weight swap; training round / outer boundary — in a
fence-timed section keyed by the same ``(kind, bucket, layout)``
scheme ``Engine.compile_counts()`` already uses, so "where do the
device-seconds go" has a scrapeable answer per executable instead of
one coarse decode-tick histogram.

Two ledgers, partitioned — every measured second lands in exactly one:

- ``device_seconds`` — warm dispatches of an already-compiled program.
- ``compile_seconds`` — the FIRST dispatch of each program key. Static
  shapes mean one key is one executable, so the first fence-timed
  section is the one that traces and compiles; booking it separately
  keeps warm-path rates honest (the first decode tick is ~1000x a warm
  one) and gives compile time its own budget line, the way the goodput
  ledger books ``compile_warmup``.

The sections are host-side fences (``perf_counter`` around a dispatch
that blocks on its outputs): on CPU they measure host compute, on an
accelerator dispatch + device execution. That is the honest contract
PERF.md records — attribution *structure* is pinned everywhere, the
absolute magnitudes are a chip-sitting claim.

``devtime_families()`` renders the two counter families
(``nanodiloco_device_seconds_total{program=...}`` /
``nanodiloco_compile_seconds_total{program=...}``) for BOTH /metrics
servers (serve's and the trainer's telemetry endpoint) from one
snapshot shape, so the exposition cannot drift between tiers.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable


def program_key(kind: str, bucket: int, layout: str) -> str:
    """One program's ledger key: ``kind:bucket:layout`` — the same
    naming ``Engine.compile_counts()`` reports cache sizes under, so an
    operator can line up "how many executables" with "how many seconds"
    without a translation table."""
    return f"{kind}:{int(bucket)}:{layout}"


class DispatchAccountant:
    """Thread-safe per-program device/compile-second ledgers.

    ``clock`` is injectable (tests drive sections with a scripted
    clock); all mutation is lock-guarded — the serve tick thread
    records while HTTP scrape threads snapshot."""

    def __init__(self, clock: Callable[[], float] = time.perf_counter) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._device_s: dict[str, float] = {}
        self._compile_s: dict[str, float] = {}
        self._dispatches: dict[str, int] = {}

    @contextmanager
    def section(self, kind: str, bucket: int, layout: str,
                *, first_is_compile: bool = True):
        """Fence-timed section around one program dispatch. The caller
        must block on the dispatch's outputs INSIDE the section (the
        fence is what makes the measurement mean anything under async
        dispatch). With ``first_is_compile`` (the default for jitted
        programs) the key's first section lands in the compile ledger;
        pass False for sites that never compile (weight swap is
        ``device_put`` + validation, warm from the start)."""
        t0 = self._clock()
        try:
            yield
        finally:
            self.record(kind, bucket, layout, self._clock() - t0,
                        first_is_compile=first_is_compile)

    def record(self, kind: str, bucket: int, layout: str, seconds: float,
               *, first_is_compile: bool = True) -> None:
        """Book an already-measured fence-timed duration (call sites
        that time their dispatch anyway — the training loop's round
        fence — record the same number here rather than double-timing)."""
        key = program_key(kind, bucket, layout)
        s = max(0.0, float(seconds))
        with self._lock:
            n = self._dispatches.get(key, 0)
            self._dispatches[key] = n + 1
            if n == 0 and first_is_compile:
                self._compile_s[key] = self._compile_s.get(key, 0.0) + s
            else:
                self._device_s[key] = self._device_s.get(key, 0.0) + s

    def snapshot(self) -> dict:
        """The stats-JSONL / ``scheduler.stats()`` shape: rounded
        per-program ledgers plus dispatch counts. Keys sorted so the
        JSONL diffs cleanly run to run."""
        with self._lock:
            return {
                "device_seconds_by_program": {
                    k: round(v, 6) for k, v in sorted(self._device_s.items())
                },
                "compile_seconds_by_program": {
                    k: round(v, 6) for k, v in sorted(self._compile_s.items())
                },
                "dispatches_by_program": dict(sorted(self._dispatches.items())),
            }

    def total_device_seconds(self) -> float:
        """Warm-dispatch seconds across every program (the serve bench's
        measured-window numerator, via snapshot deltas)."""
        with self._lock:
            return sum(self._device_s.values())

    def reset(self) -> None:
        """Zero every ledger AND the first-dispatch memory — warm-up
        traffic (``Engine.warm_spec``, bench warm legs) must not leak
        into measured windows, the same contract as
        ``reset_spec_stats``. Compile state resets too: a post-reset
        first dispatch of a key is warm in reality (the executable is
        cached), so callers that want compile seconds kept should
        snapshot before resetting."""
        with self._lock:
            self._device_s.clear()
            self._compile_s.clear()
            self._dispatches.clear()

    def reset_device_seconds(self) -> None:
        """Zero the warm-dispatch ledger but KEEP compile seconds and
        the first-dispatch memory: warmup traffic (``warm_spec``'s
        ramp) is exactly when programs compile — those seconds are real
        and stay — while its throwaway warm ticks must not leak into
        the device-second budget, the ``reset_spec_stats`` contract."""
        with self._lock:
            self._device_s.clear()


def devtime_families(snapshot: dict | None) -> list:
    """``render_exposition`` families for one accountant snapshot —
    shared by the serve server, the trainer's telemetry endpoint, and
    the fleet router so ``nanodiloco_device_seconds`` /
    ``nanodiloco_compile_seconds`` are ONE family definition everywhere
    (the metrics-name lint depends on that)."""
    if not snapshot:
        return []
    families: list = []
    dev = snapshot.get("device_seconds_by_program") or {}
    if dev:
        families.append((
            "nanodiloco_device_seconds", "counter",
            "fence-timed seconds in warm compiled-program dispatches, "
            "by program (kind:bucket:layout — compile_counts keying)",
            [({"program": k}, v) for k, v in sorted(dev.items())]
            + [(None, round(sum(dev.values()), 6))],
        ))
    comp = snapshot.get("compile_seconds_by_program") or {}
    if comp:
        families.append((
            "nanodiloco_compile_seconds", "counter",
            "fence-timed seconds in each program's FIRST dispatch "
            "(trace + XLA compile under static shapes), by program",
            [({"program": k}, v) for k, v in sorted(comp.items())]
            + [(None, round(sum(comp.values()), 6))],
        ))
    return families
