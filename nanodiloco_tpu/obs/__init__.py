"""Host-side observability: span tracer, training watchdog, live status.

The DiLoCo value proposition is a RATIO — compute time over
communication time (arXiv:2311.08105) — and a production run must be
able to show where every millisecond of a round goes (``tracer``), be
alerted when the run silently degrades (``watchdog``), and account for
every wire byte the outer sync moves (``Diloco.sync_wire_bytes``).
Everything here is pure host-side Python: no jax imports, no device
work, safe to run on every step of a training loop.
"""

from nanodiloco_tpu.obs.tracer import SpanTracer, current_tracer, set_tracer, trace_span
from nanodiloco_tpu.obs.watchdog import Watchdog, WatchdogConfig

__all__ = [
    "SpanTracer",
    "current_tracer",
    "set_tracer",
    "trace_span",
    "Watchdog",
    "WatchdogConfig",
]
