"""Host-side observability: tracer, watchdog, telemetry, cost analytics.

The DiLoCo value proposition is a RATIO — compute time over
communication time (arXiv:2311.08105) — and a production run must be
able to show where every millisecond of a round goes (``tracer``), be
alerted when the run silently degrades (``watchdog``), account for
every wire byte the outer sync moves (``Diloco.sync_wire_bytes``),
answer a live scrape over HTTP (``telemetry``), and reconcile measured
throughput against what XLA says the program costs (``costs``).
Everything here is stdlib host-side Python — no new dependencies, no
device work; only ``costs`` touches jax, and lazily, to read the
compiler's own cost model.

Observation feeds ACTION: the watchdog's fatal alarms (stall/NaN) can
trigger the resilience stack's emergency checkpoint-and-exit via its
``on_fatal`` callback (``--watch-action checkpoint-exit``), and the
telemetry endpoint carries the resilience counters (faults fired, IO
retries, resumes, supervisor restarts) alongside the training gauges —
see ``nanodiloco_tpu/resilience``.
"""

from nanodiloco_tpu.obs.collector import (
    Collector,
    SeriesStore,
    flatten_families,
    parse_exposition,
)
from nanodiloco_tpu.obs.flightrec import FlightRecorder
from nanodiloco_tpu.obs.forecast import CapacityEstimate, CapacityModel
from nanodiloco_tpu.obs.goodput import CAUSES as GOODPUT_CAUSES
from nanodiloco_tpu.obs.goodput import (
    FLEET_STATE_CAUSES,
    GoodputLedger,
    stitch_goodput_records,
)
from nanodiloco_tpu.obs.tracer import (
    SpanTracer,
    current_tracer,
    merge_chrome_traces,
    set_tracer,
    trace_shard_path,
    trace_span,
)
from nanodiloco_tpu.obs.slo import SLOMonitor, SLORule, standard_rules
from nanodiloco_tpu.obs.watchdog import Watchdog, WatchdogConfig
from nanodiloco_tpu.obs.telemetry import (
    Histogram,
    TelemetryServer,
    capture_live_profile,
    parse_metrics_text,
    render_exposition,
)

__all__ = [
    "CapacityEstimate",
    "CapacityModel",
    "Collector",
    "FLEET_STATE_CAUSES",
    "SeriesStore",
    "flatten_families",
    "parse_exposition",
    "SLOMonitor",
    "SLORule",
    "standard_rules",
    "FlightRecorder",
    "GoodputLedger",
    "GOODPUT_CAUSES",
    "stitch_goodput_records",
    "SpanTracer",
    "current_tracer",
    "merge_chrome_traces",
    "set_tracer",
    "trace_shard_path",
    "trace_span",
    "Watchdog",
    "WatchdogConfig",
    "Histogram",
    "TelemetryServer",
    "capture_live_profile",
    "parse_metrics_text",
    "render_exposition",
]
