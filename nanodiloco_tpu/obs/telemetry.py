"""Live telemetry endpoint: /metrics (OpenMetrics) + /healthz, stdlib only.

A production pod is SCRAPED, not tailed: a Prometheus poller, a load
balancer's health check, or an operator's curl must be able to ask a
RUNNING job "are you healthy, what is your round budget, how many wire
bytes have you moved" without ssh-ing in and parsing an unbounded
JSONL. This server is ``http.server`` on a daemon thread — no new
dependencies, nothing when the port is unset — and its gauges are fed
from the SAME ``MetricsLogger.log()`` path that writes the JSONL, so
the scrape and the file can never tell different stories.

Endpoints:
- ``GET /metrics`` — OpenMetrics text: last loss/eval loss/tokens-per-
  sec/comm-share, wire bytes (per-sync gauge + running total), per-
  phase round-budget seconds (``phase`` label), alarm counters by
  ``kind``, HBM peak, outer-sync count, step, analytic FLOPs/token
  when a cost record was captured.
- ``GET /healthz`` — 200/503 + the watchdog's status document (the
  same state ``--status-file`` writes, now pull-able). 503 when the
  run is stalled or crashed, or when a ``nan_loss`` alarm has fired (a
  NaN poisons every later step — the job is unhealthy even though the
  loop still turns). Loss spikes and throughput dips stay 200: they
  are alerts, not liveness failures.
- ``POST /debug/profile?seconds=N`` — capture a ``jax.profiler`` trace
  of the LIVE process into the run's profile directory and return its
  path (``capture_live_profile``). Guarded: one capture at a time
  (409 when busy), bounded duration, 404 unless a profile directory
  was configured.

The server binds ``port`` on all interfaces (a scraper is usually not
on the host); ``port=0`` picks a free port, exposed as ``.port`` (and
printed by the train loop) — the form tests and one-off runs use.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

# JSONL key -> (metric name, help). All gauges: "last observed value".
_GAUGE_KEYS = {
    "loss": ("nanodiloco_loss", "last logged training loss"),
    "eval_loss": ("nanodiloco_eval_loss", "last held-out eval loss"),
    "perplexity": ("nanodiloco_perplexity", "last training perplexity"),
    "lr": ("nanodiloco_lr", "current inner learning rate"),
    "step": ("nanodiloco_step", "last logged real (inner) step"),
    "tokens_per_sec": (
        "nanodiloco_tokens_per_sec", "cumulative training throughput"
    ),
    "comm_share": (
        "nanodiloco_comm_share",
        "outer-sync share of wall clock (the DiLoCo ratio)",
    ),
    "avg_sync_time_s": (
        "nanodiloco_avg_sync_time_seconds", "mean outer-sync wall clock"
    ),
    "wire_bytes_per_sync": (
        "nanodiloco_wire_bytes_per_sync", "per-worker wire bytes per outer sync"
    ),
    "hbm_peak_bytes": (
        "nanodiloco_hbm_peak_bytes", "peak device memory in use"
    ),
    "quarantined_workers": (
        "nanodiloco_quarantined_workers", "workers masked out of the last sync"
    ),
    # elastic DiLoCo (training/elastic.py): the live fleet width and
    # per-worker realized inner steps — the scrapeable view of
    # join/shrink and straggler demotions
    "workers_active": (
        "nanodiloco_workers_active",
        "workers contributing to the last outer sync",
    ),
    # DiLoCo dynamics metrics (parallel/diloco.py::_sync_dynamics):
    # drift, momentum, and update-alignment — the quantities quantized
    # outer comm needs to stay tame (arXiv:2501.18512)
    "drift_max": (
        "nanodiloco_drift_max",
        "max pairwise worker replica distance / snapshot norm at the "
        "last sync",
    ),
    "drift_mean": (
        "nanodiloco_drift_mean",
        "RMS pairwise worker replica distance / snapshot norm at the "
        "last sync",
    ),
    "outer_momentum_norm": (
        "nanodiloco_outer_momentum_norm",
        "outer Nesterov momentum norm after the last sync",
    ),
    "outer_update_cos": (
        "nanodiloco_outer_update_cos",
        "cosine(mean pseudo-gradient, applied outer update descent "
        "direction) at the last sync",
    ),
    # async delayed-apply outer step (parallel/diloco.py async_outer):
    # rounds between the applied merge's launch and its apply — the
    # realized staleness of the overlap (streaming logs its fragment
    # stagger here as a fraction of a round)
    "outer_staleness": (
        "nanodiloco_outer_staleness",
        "rounds the last applied outer merge landed late "
        "(async delayed-apply / streaming stagger)",
    ),
}


# -- histograms (OpenMetrics cumulative-bucket form) --------------------------

# latency buckets in seconds: sub-ms to a minute, the span a serving
# TTFT / queue-wait / decode-tick distribution actually occupies
DEFAULT_TIME_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def nearest_rank_percentile(sorted_vals, p: float):
    """Standard nearest-rank percentile over an ascending-sorted list:
    the smallest value with at least ``ceil(p*n)`` observations at or
    below it; None on empty input. ONE implementation for every
    window-percentile consumer (the serve scheduler's TTFT gauges,
    ``scripts/serve_bench.py``'s client-side stats) — the biased
    ``int(p*n)`` indexing both used to hand-roll read p50 of two
    samples as the larger one."""
    if not sorted_vals:
        return None
    k = max(0, math.ceil(p * len(sorted_vals)) - 1)
    return sorted_vals[min(len(sorted_vals) - 1, k)]


class Histogram:
    """Fixed-bucket cumulative histogram (the OpenMetrics shape: every
    bucket counts observations <= its upper bound, ``+Inf`` counts all).
    Thread-safe: the serve tick thread observes while HTTP threads
    snapshot. Gauge-window percentiles (the PR-4 TTFT snapshot) answered
    "what was p95 over the last 512 requests"; a real histogram lets a
    scraper compute rates and quantiles over ANY window, aggregated
    across processes — the difference between a demo metric and one
    Prometheus can actually alert on."""

    def __init__(self, buckets=DEFAULT_TIME_BUCKETS) -> None:
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("a histogram needs at least one bucket bound")
        if len(set(bounds)) != len(bounds):
            raise ValueError(f"duplicate bucket bounds: {buckets}")
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = overflow (+Inf only)
        # one exemplar per bucket: (trace_id, observed value) of the
        # LAST sampled observation that landed there — bounded memory
        # (len(bounds)+1 slots), the metrics→trace link per bucket
        self._exemplars: list[tuple[str, float] | None] = (
            [None] * (len(bounds) + 1)
        )
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float, exemplar: str | None = None) -> None:
        """Record one observation. ``exemplar`` (optional) is the trace
        id of the request that produced it — kept one-per-bucket, last
        writer wins, so the exposition can link a latency bucket back
        to a concrete sampled trace. Pass None (the default) for
        unsampled observations: the counts still move, only the link
        is withheld."""
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            if exemplar:
                self._exemplars[i] = (str(exemplar), v)
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        """``{"buckets": [(le, cumulative), ..., ("+Inf", count)],
        "count": n, "sum": s}`` — the exposition-ready cumulative form.
        When any exemplar was recorded the dict also carries
        ``"exemplars": {le: (trace_id, value)}`` keyed by the bucket
        each exemplar LANDED in (exemplars are per-bucket, not
        cumulative — OpenMetrics requires an exemplar's value to lie
        inside its bucket's range)."""
        with self._lock:
            counts = list(self._counts)
            exemplars = list(self._exemplars)
            total, s = self._count, self._sum
        cum = 0
        buckets: list[tuple[float | str, int]] = []
        ex: dict[float | str, tuple[str, float]] = {}
        for j, (bound, c) in enumerate(zip(self.bounds, counts)):
            cum += c
            buckets.append((bound, cum))
            if exemplars[j] is not None:
                ex[bound] = exemplars[j]
        buckets.append(("+Inf", total))
        if exemplars[-1] is not None:
            ex["+Inf"] = exemplars[-1]
        snap: dict = {"buckets": buckets, "count": total, "sum": s}
        if ex:
            snap["exemplars"] = ex
        return snap


class TelemetryServer:
    """Scrapeable mirror of the metrics stream. ``observe(rec)`` is
    called by ``MetricsLogger.log`` with every record (metrics AND
    alarms — one source of truth); ``health_fn`` returns the watchdog's
    status document on each /healthz hit (live state, not a cached
    copy). Thread-safe: the HTTP threads read under the same lock the
    train loop writes under."""

    def __init__(
        self,
        port: int = 0,
        host: str = "0.0.0.0",
        health_fn: Callable[[], dict] | None = None,
        profile_dir: str | None = None,
    ) -> None:
        self._health_fn = health_fn
        # on-demand live profiling: POST /debug/profile?seconds=N
        # captures a jax.profiler trace from THIS process into
        # ``profile_dir`` (None = the endpoint answers 404 — profiling
        # must be an operator opt-in, the capture is heavyweight)
        self.profile_dir = profile_dir
        self._lock = threading.Lock()
        self._gauges: dict[str, float] = {}
        self._worker_pg: dict[int, float] = {}  # worker -> last pg norm
        self._worker_h: dict[int, float] = {}   # worker -> realized H
        self._elastic: dict[str, int] = {}      # elastic records by kind
        self._phases: dict[str, float] = {}
        self._badput: dict[str, float] = {}  # cause -> cumulative seconds
        self._alarms: dict[str, int] = {}
        self._faults: dict[str, int] = {}    # injected-fault records by kind
        self._retries: dict[str, int] = {}   # IO retry records by op
        self._devtime: dict | None = None    # last devtime snapshot
        self._resumes = 0                    # checkpoint-resume records
        self._outer_syncs = 0
        self._wire_total = 0.0
        self._thread: threading.Thread | None = None

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # a scrape must not spam stdout
                pass

            def _reply(self, code, body, ctype):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = server.render_metrics().encode()
                    ctype = OPENMETRICS_CONTENT_TYPE
                    code = 200
                elif path == "/healthz":
                    code, doc = server.health()
                    body = (json.dumps(doc) + "\n").encode()
                    ctype = "application/json"
                else:
                    code, body, ctype = 404, b"not found\n", "text/plain"
                self._reply(code, body, ctype)

            def do_POST(self):
                if self.path.split("?", 1)[0] != "/debug/profile":
                    self._reply(404, b"not found\n", "text/plain")
                    return
                code, doc = handle_profile_request(
                    server.profile_dir, self.path
                )
                self._reply(
                    code, (json.dumps(doc) + "\n").encode(),
                    "application/json",
                )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "TelemetryServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="nanodiloco-telemetry",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- ingest (the MetricsLogger.log path) ---------------------------------

    def observe(self, rec: dict[str, Any]) -> None:
        with self._lock:
            for k, v in rec.items():
                if v is None:
                    continue
                if k == "alarm":
                    self._alarms[str(v)] = self._alarms.get(str(v), 0) + 1
                elif k == "fault":
                    self._faults[str(v)] = self._faults.get(str(v), 0) + 1
                elif k == "retry":
                    self._retries[str(v)] = self._retries.get(str(v), 0) + 1
                elif k == "resume":
                    self._resumes += 1
                elif k == "restart_count" and isinstance(v, (int, float)):
                    # supervisor-side restart counter, carried in by the
                    # resume record so a scrape sees restart pressure
                    self._gauges["nanodiloco_restarts"] = float(v)
                elif k == "outer_synced":
                    self._outer_syncs += int(bool(v))
                elif k == "wire_bytes_total":
                    self._wire_total = float(v)
                elif k == "pg_norm" and isinstance(v, (list, tuple)):
                    # per-worker pseudo-gradient norms from the sync's
                    # dynamics record -> one labeled gauge per worker
                    for w, nv in enumerate(v):
                        if isinstance(nv, (int, float)):
                            self._worker_pg[w] = float(nv)
                elif k == "elastic":
                    # elastic DiLoCo decisions by kind (straggler
                    # demote/restore, resize absorbed at resume) — the
                    # demotion total is its own headline counter
                    self._elastic[str(v)] = self._elastic.get(str(v), 0) + 1
                elif k == "inner_steps_realized" and isinstance(
                    v, (list, tuple)
                ):
                    # a resize drops/adds workers: the realized-H gauge
                    # family must track the CURRENT fleet, not keep
                    # ghost series for departed workers
                    self._worker_h = {
                        w: float(nv) for w, nv in enumerate(v)
                        if isinstance(nv, (int, float))
                    }
                elif k == "goodput" and isinstance(v, dict):
                    # goodput ledger snapshot (obs/goodput): the
                    # fraction as a gauge, every badput cause's
                    # cumulative seconds as a labeled counter family —
                    # the scrapeable wall-clock budget
                    gf = v.get("goodput_fraction")
                    if isinstance(gf, (int, float)):
                        self._gauges["nanodiloco_goodput_fraction"] = float(gf)
                    from nanodiloco_tpu.obs.goodput import CAUSES

                    for cause in CAUSES:
                        if cause == "compute":
                            continue
                        s = v.get(f"{cause}_s")
                        if isinstance(s, (int, float)):
                            self._badput[cause] = float(s)
                elif k.startswith("t_") and isinstance(v, (int, float)):
                    self._phases[k[2:]] = float(v)
                elif k == "devtime" and isinstance(v, dict):
                    # DispatchAccountant snapshot (obs/devtime): the
                    # ledgers are cumulative, so keeping the LAST
                    # snapshot renders correct counters
                    self._devtime = v
                elif k == "cost_analysis" and isinstance(v, dict):
                    fpt = v.get("flops_per_token")
                    if isinstance(fpt, (int, float)):
                        self._gauges["nanodiloco_flops_per_token"] = float(fpt)
                elif k in _GAUGE_KEYS and isinstance(v, (int, float)):
                    self._gauges[_GAUGE_KEYS[k][0]] = float(v)

    # -- render --------------------------------------------------------------

    def render_metrics(self) -> str:
        """OpenMetrics text via the shared ``render_exposition`` (the
        serve endpoint, nanodiloco_tpu/serve/server.py, uses the same
        renderer so every /metrics in the project speaks one dialect)."""
        with self._lock:
            gauges = dict(self._gauges)
            worker_pg = dict(self._worker_pg)
            worker_h = dict(self._worker_h)
            elastic = dict(self._elastic)
            phases = dict(self._phases)
            badput = dict(self._badput)
            alarms = dict(self._alarms)
            faults = dict(self._faults)
            retries = dict(self._retries)
            resumes = self._resumes
            syncs = self._outer_syncs
            wire_total = self._wire_total
            devtime = self._devtime
        helps = {name: h for name, h in _GAUGE_KEYS.values()}
        helps["nanodiloco_flops_per_token"] = (
            "analytic FLOPs per token from the lowered program's "
            "XLA cost analysis"
        )
        helps["nanodiloco_restarts"] = (
            "supervisor restarts preceding this process (from the "
            "resume record)"
        )
        helps["nanodiloco_goodput_fraction"] = (
            "fraction of this lifetime's wall-clock attributed to "
            "compute (obs/goodput ledger)"
        )
        families: list = [
            (name, "gauge", helps.get(name), [(None, gauges[name])])
            for name in sorted(gauges)
        ]
        if worker_pg:
            families.append((
                "nanodiloco_worker_pg_norm", "gauge",
                "per-worker pseudo-gradient norm at the last outer sync",
                [({"worker": str(w)}, worker_pg[w])
                 for w in sorted(worker_pg)],
            ))
        if worker_h:
            families.append((
                "nanodiloco_inner_steps_realized", "gauge",
                "per-worker realized inner steps in the last round "
                "(elastic DiLoCo heterogeneous H)",
                [({"worker": str(w)}, worker_h[w])
                 for w in sorted(worker_h)],
            ))
        if elastic:
            families.append((
                "nanodiloco_straggler_demotions", "counter",
                "straggler-policy demotions observed (elastic records "
                "of kind straggler_demote)",
                [(None, elastic.get("straggler_demote", 0))],
            ))
            families.append((
                "nanodiloco_elastic_events", "counter",
                "elastic DiLoCo records by kind (straggler "
                "demote/restore, resize absorbed at resume, schedule "
                "reset)",
                [({"kind": k}, elastic[k]) for k in sorted(elastic)]
                + [(None, sum(elastic.values()))],
            ))
        if phases:
            families.append((
                "nanodiloco_phase_seconds", "gauge",
                "last round's host-side phase budget",
                [({"phase": ph}, phases[ph]) for ph in sorted(phases)],
            ))
        if badput:
            families.append((
                "nanodiloco_badput_seconds", "counter",
                "this lifetime's wall-clock seconds NOT spent computing, "
                "by attributed cause (obs/goodput ledger)",
                [({"cause": c}, badput[c]) for c in sorted(badput)],
            ))
        # resilience counters: alarms/injected faults by kind, IO retries
        # by op, checkpoint resumes — the scrapeable fault timeline
        for name, help_text, label, by in (
            ("nanodiloco_alarms", "watchdog alarms by kind", "kind", alarms),
            ("nanodiloco_faults", "injected faults fired, by kind", "kind",
             faults),
            ("nanodiloco_retries", "IO retry attempts, by operation", "op",
             retries),
        ):
            families.append((
                name, "counter", help_text,
                [({label: k}, by[k]) for k in sorted(by)]
                + [(None, sum(by.values()))],
            ))
        families.append(("nanodiloco_resumes", "counter",
                         "checkpoint resumes observed by this process",
                         [(None, resumes)]))
        families.append(("nanodiloco_outer_syncs", "counter",
                         "outer syncs completed",
                         [(None, syncs)]))
        families.append((
            "nanodiloco_wire_bytes", "counter",
            "cumulative per-worker outer-sync wire bytes",
            [(None, wire_total)],
        ))
        # per-program device/compile-second ledgers (obs/devtime): the
        # SAME family definition the serve /metrics uses, so the two
        # tiers' expositions cannot drift
        from nanodiloco_tpu.obs.devtime import devtime_families

        families.extend(devtime_families(devtime))
        return render_exposition(families)

    def health(self) -> tuple[int, dict]:
        """(status code, document). Unhealthy (503) = stalled, crashed,
        or any ``nan_loss`` alarm on record; everything else — spikes,
        throughput dips, a finished run — reports 200 with the detail
        in the body."""
        if self._health_fn is None:
            return 200, {"state": "unknown", "healthy": True}
        try:
            doc = dict(self._health_fn())
        except Exception as e:  # a broken probe is itself unhealthy
            return 503, {"state": "error", "healthy": False, "error": str(e)}
        kinds = doc.get("alarm_kinds") or {}
        unhealthy = (
            doc.get("state") in ("stalled", "crashed")
            or kinds.get("nan_loss", 0) > 0
        )
        doc["healthy"] = not unhealthy
        return (503 if unhealthy else 200), doc


# -- on-demand live profiling (/debug/profile) --------------------------------

# jax.profiler's trace machinery is process-global: exactly one capture
# may run at a time (a second start_trace raises), and the startup
# --profile-dir window uses the same machinery. One lock + a monotonic
# capture counter keep concurrent POSTs (and repeated captures into the
# same dir) from trampling each other.
_PROFILE_LOCK = threading.Lock()
_PROFILE_SEQ = [0]
PROFILE_MAX_SECONDS = 60.0


def acquire_profiler_window() -> None:
    """Blocking-acquire the process-global profiler for a planned trace
    window (the train loop's startup ``--profile-dir`` capture). While
    held, live ``/debug/profile`` captures answer 409; conversely a live
    capture in flight makes this WAIT (bounded by
    ``PROFILE_MAX_SECONDS``) instead of letting the planned
    ``jax.profiler.start_trace`` crash on 'already started'. Pair every
    acquire with ``release_profiler_window``."""
    _PROFILE_LOCK.acquire()


def release_profiler_window() -> None:
    _PROFILE_LOCK.release()


def capture_live_profile(out_dir: str, seconds: float) -> dict:
    """Capture a ``jax.profiler`` trace of THIS live process for
    ``seconds`` into a fresh subdirectory of ``out_dir`` and return
    ``{"trace_dir", "seconds"}`` — the missing half of ``--profile-dir``
    (startup-only): the one time profiling matters is when a RUNNING
    job misbehaves, and restarting it to profile destroys the evidence.

    Raises RuntimeError when a capture is already in progress (here or
    the startup window) and ValueError on an out-of-range duration.
    The sleep happens on the caller's thread (an HTTP handler thread on
    the serving/telemetry endpoints) — training/serving dispatch is
    NEVER blocked; the profiler collects from the live threads."""
    seconds = float(seconds)
    if not 0.0 < seconds <= PROFILE_MAX_SECONDS:
        raise ValueError(
            f"seconds must be in (0, {PROFILE_MAX_SECONDS:g}]; got {seconds}"
        )
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise RuntimeError("a profile capture is already in progress")
    try:
        import jax

        _PROFILE_SEQ[0] += 1
        trace_dir = os.path.join(out_dir, f"capture-{_PROFILE_SEQ[0]:03d}")
        os.makedirs(trace_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception as e:
            # the startup --profile-dir window (or an embedder's trace)
            # holds the global profiler — busy, not broken
            raise RuntimeError(f"profiler unavailable: {e}") from e
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        return {"trace_dir": trace_dir, "seconds": seconds}
    finally:
        _PROFILE_LOCK.release()


def handle_profile_request(
    profile_dir: str | None, raw_path: str
) -> tuple[int, dict]:
    """Shared POST /debug/profile handler body for the telemetry and
    serving endpoints: parse ``?seconds=N`` (default 2), run the
    capture, map failures to HTTP semantics (404 endpoint disabled,
    400 bad duration, 409 capture already running)."""
    if profile_dir is None:
        return 404, {
            "error": "live profiling is not configured on this server "
                     "(no profile directory)"
        }
    q = parse_qs(urlparse(raw_path).query)
    try:
        seconds = float(q.get("seconds", ["2"])[0])
    except ValueError:
        return 400, {"error": f"bad seconds value: {q['seconds'][0]!r}"}
    try:
        return 200, capture_live_profile(profile_dir, seconds)
    except ValueError as e:
        return 400, {"error": str(e)}
    except RuntimeError as e:
        return 409, {"error": str(e)}
    except Exception as e:  # a broken profiler must not kill the server
        return 500, {"error": f"{type(e).__name__}: {e}"}


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() and abs(v) < 2**53 else repr(v)


def escape_label_value(v: Any) -> str:
    """OpenMetrics label-value escaping: backslash, double-quote, and
    line feed are the three characters the spec's ABNF escapes. A
    CARRIAGE RETURN is escaped too (``\\r``, a dialect extension the
    parser in ``obs/collector`` inverts): the spec simply forbids raw
    CR, and emitting one TEARS the line-oriented exposition for every
    ``splitlines()``-based consumer — a label value fed from operator
    input (an error string off an HTTP response ends ``\\r\\n``) used
    to silently corrupt the scrape into garbage keys. Everything else
    passes through."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
        .replace("\r", "\\r")
    )


def _escape_help(text: str) -> str:
    """HELP-text escaping (backslash and line feed — CR too, same
    torn-line hazard as label values; quotes are legal in help)."""
    return (
        str(text).replace("\\", "\\\\").replace("\n", "\\n")
        .replace("\r", "\\r")
    )


def _render_labels(labels: dict[str, Any]) -> str:
    return ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in labels.items()
    )


def render_exposition(families) -> str:
    """OpenMetrics text from ``(name, type, help, samples)`` families.

    - gauge/counter: ``samples`` is ``[(labels_or_None, value)]`` with
      ``labels`` a dict — values are escaped here (``\\``, ``"`` and
      newline per the spec), so callers never hand-render label strings.
      Counters follow the spec's family-name / ``_total``-sample split.
    - histogram: ``samples`` is a ``Histogram.snapshot()`` dict —
      rendered as the cumulative ``_bucket{le=...}`` series plus
      ``_count`` and ``_sum`` — or a list of
      ``(labels_or_None, snapshot)`` pairs for a labeled histogram
      family (e.g. the serve queue-wait split by ``priority``); each
      pair's labels ride on every ``_bucket``/``_count``/``_sum``
      sample of its series, with ``le`` appended last. A snapshot's
      optional ``"exemplars"`` map (``{le: (trace_id, value)}``)
      renders as OpenMetrics exemplars on the matching ``_bucket``
      lines — ``... # {trace_id="..."} value`` — linking the bucket to
      a sampled trace.

    Every family gets ``# HELP`` and ``# TYPE`` metadata (HELP text
    escaped); ``# EOF`` terminates the exposition (a truncated scrape
    must be detectable as truncated). Shared by the training telemetry
    endpoint above and the serving endpoint
    (nanodiloco_tpu/serve/server.py) — one dialect everywhere."""
    lines: list[str] = []
    for name, mtype, help_text, samples in families:
        lines.append(f"# HELP {name} {_escape_help(help_text or name)}")
        lines.append(f"# TYPE {name} {mtype}")
        if mtype == "histogram":
            series = [(None, samples)] if isinstance(samples, dict) else samples
            for labels, snap in series:
                base = _render_labels(labels) + "," if labels else ""
                suffix = f"{{{_render_labels(labels)}}}" if labels else ""
                exemplars = snap.get("exemplars") or {}
                for le, cum in snap["buckets"]:
                    le_s = le if isinstance(le, str) else _fmt(float(le))
                    line = f'{name}_bucket{{{base}le="{le_s}"}} {int(cum)}'
                    ex = exemplars.get(le)
                    if ex is not None:
                        # OpenMetrics exemplar: " # {labels} value" —
                        # the trace id of a sampled observation that
                        # landed in THIS bucket (value inside its range)
                        tid, ev = ex
                        line += (
                            f' # {{trace_id="{escape_label_value(tid)}"}}'
                            f" {_fmt(float(ev))}"
                        )
                    lines.append(line)
                lines.append(f"{name}_count{suffix} {int(snap['count'])}")
                lines.append(f"{name}_sum{suffix} {_fmt(float(snap['sum']))}")
            continue
        sample_name = name + "_total" if mtype == "counter" else name
        for labels, value in samples:
            if labels:
                lines.append(
                    f"{sample_name}{{{_render_labels(labels)}}} {_fmt(value)}"
                )
            else:
                lines.append(f"{sample_name} {_fmt(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_metrics_text(text: str) -> dict[str, float]:
    """Parse an OpenMetrics exposition into ``{sample_name: value}``
    with the label set in the key in CANONICAL rendered form (e.g.
    ``nanodiloco_alarms_total{kind="nan_loss"}``). The consumer half of
    the scrape loop (tests, chip_agenda's telemetry phase) — tolerant
    of unknown lines. Built on the structured scanner in
    ``obs/collector``: the old ``rpartition(" ")`` shortcut silently
    mis-keyed any sample whose label VALUE carried an escaped newline
    (the rendered ``\\n`` splits the line in ``splitlines``-based
    consumers) and could not tell an escaped quote from the value
    delimiter — the renderer escapes correctly, so the parser must
    unescape correctly or the dialect does not round-trip."""
    from nanodiloco_tpu.obs.collector import parse_sample_line, sample_key

    out: dict[str, float] = {}
    for line in text.split("\n"):
        try:
            name, labels, value = parse_sample_line(line)
        except (ValueError, IndexError):
            continue
        out[sample_key(name, labels)] = value
    return out
