"""Live telemetry endpoint: /metrics (OpenMetrics) + /healthz, stdlib only.

A production pod is SCRAPED, not tailed: a Prometheus poller, a load
balancer's health check, or an operator's curl must be able to ask a
RUNNING job "are you healthy, what is your round budget, how many wire
bytes have you moved" without ssh-ing in and parsing an unbounded
JSONL. This server is ``http.server`` on a daemon thread — no new
dependencies, nothing when the port is unset — and its gauges are fed
from the SAME ``MetricsLogger.log()`` path that writes the JSONL, so
the scrape and the file can never tell different stories.

Endpoints:
- ``GET /metrics`` — OpenMetrics text: last loss/eval loss/tokens-per-
  sec/comm-share, wire bytes (per-sync gauge + running total), per-
  phase round-budget seconds (``phase`` label), alarm counters by
  ``kind``, HBM peak, outer-sync count, step, analytic FLOPs/token
  when a cost record was captured.
- ``GET /healthz`` — 200/503 + the watchdog's status document (the
  same state ``--status-file`` writes, now pull-able). 503 when the
  run is stalled or crashed, or when a ``nan_loss`` alarm has fired (a
  NaN poisons every later step — the job is unhealthy even though the
  loop still turns). Loss spikes and throughput dips stay 200: they
  are alerts, not liveness failures.

The server binds ``port`` on all interfaces (a scraper is usually not
on the host); ``port=0`` picks a free port, exposed as ``.port`` (and
printed by the train loop) — the form tests and one-off runs use.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

# JSONL key -> (metric name, help). All gauges: "last observed value".
_GAUGE_KEYS = {
    "loss": ("nanodiloco_loss", "last logged training loss"),
    "eval_loss": ("nanodiloco_eval_loss", "last held-out eval loss"),
    "perplexity": ("nanodiloco_perplexity", "last training perplexity"),
    "lr": ("nanodiloco_lr", "current inner learning rate"),
    "step": ("nanodiloco_step", "last logged real (inner) step"),
    "tokens_per_sec": (
        "nanodiloco_tokens_per_sec", "cumulative training throughput"
    ),
    "comm_share": (
        "nanodiloco_comm_share",
        "outer-sync share of wall clock (the DiLoCo ratio)",
    ),
    "avg_sync_time_s": (
        "nanodiloco_avg_sync_time_seconds", "mean outer-sync wall clock"
    ),
    "wire_bytes_per_sync": (
        "nanodiloco_wire_bytes_per_sync", "per-worker wire bytes per outer sync"
    ),
    "hbm_peak_bytes": (
        "nanodiloco_hbm_peak_bytes", "peak device memory in use"
    ),
    "quarantined_workers": (
        "nanodiloco_quarantined_workers", "workers masked out of the last sync"
    ),
}


class TelemetryServer:
    """Scrapeable mirror of the metrics stream. ``observe(rec)`` is
    called by ``MetricsLogger.log`` with every record (metrics AND
    alarms — one source of truth); ``health_fn`` returns the watchdog's
    status document on each /healthz hit (live state, not a cached
    copy). Thread-safe: the HTTP threads read under the same lock the
    train loop writes under."""

    def __init__(
        self,
        port: int = 0,
        host: str = "0.0.0.0",
        health_fn: Callable[[], dict] | None = None,
    ) -> None:
        self._health_fn = health_fn
        self._lock = threading.Lock()
        self._gauges: dict[str, float] = {}
        self._phases: dict[str, float] = {}
        self._alarms: dict[str, int] = {}
        self._faults: dict[str, int] = {}    # injected-fault records by kind
        self._retries: dict[str, int] = {}   # IO retry records by op
        self._resumes = 0                    # checkpoint-resume records
        self._outer_syncs = 0
        self._wire_total = 0.0
        self._thread: threading.Thread | None = None

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # a scrape must not spam stdout
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = server.render_metrics().encode()
                    ctype = OPENMETRICS_CONTENT_TYPE
                    code = 200
                elif path == "/healthz":
                    code, doc = server.health()
                    body = (json.dumps(doc) + "\n").encode()
                    ctype = "application/json"
                else:
                    code, body, ctype = 404, b"not found\n", "text/plain"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "TelemetryServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="nanodiloco-telemetry",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- ingest (the MetricsLogger.log path) ---------------------------------

    def observe(self, rec: dict[str, Any]) -> None:
        with self._lock:
            for k, v in rec.items():
                if v is None:
                    continue
                if k == "alarm":
                    self._alarms[str(v)] = self._alarms.get(str(v), 0) + 1
                elif k == "fault":
                    self._faults[str(v)] = self._faults.get(str(v), 0) + 1
                elif k == "retry":
                    self._retries[str(v)] = self._retries.get(str(v), 0) + 1
                elif k == "resume":
                    self._resumes += 1
                elif k == "restart_count" and isinstance(v, (int, float)):
                    # supervisor-side restart counter, carried in by the
                    # resume record so a scrape sees restart pressure
                    self._gauges["nanodiloco_restarts"] = float(v)
                elif k == "outer_synced":
                    self._outer_syncs += int(bool(v))
                elif k == "wire_bytes_total":
                    self._wire_total = float(v)
                elif k.startswith("t_") and isinstance(v, (int, float)):
                    self._phases[k[2:]] = float(v)
                elif k == "cost_analysis" and isinstance(v, dict):
                    fpt = v.get("flops_per_token")
                    if isinstance(fpt, (int, float)):
                        self._gauges["nanodiloco_flops_per_token"] = float(fpt)
                elif k in _GAUGE_KEYS and isinstance(v, (int, float)):
                    self._gauges[_GAUGE_KEYS[k][0]] = float(v)

    # -- render --------------------------------------------------------------

    def render_metrics(self) -> str:
        """OpenMetrics text via the shared ``render_exposition`` (the
        serve endpoint, nanodiloco_tpu/serve/server.py, uses the same
        renderer so every /metrics in the project speaks one dialect)."""
        with self._lock:
            gauges = dict(self._gauges)
            phases = dict(self._phases)
            alarms = dict(self._alarms)
            faults = dict(self._faults)
            retries = dict(self._retries)
            resumes = self._resumes
            syncs = self._outer_syncs
            wire_total = self._wire_total
        helps = {name: h for name, h in _GAUGE_KEYS.values()}
        helps["nanodiloco_flops_per_token"] = (
            "analytic FLOPs per token from the lowered program's "
            "XLA cost analysis"
        )
        helps["nanodiloco_restarts"] = (
            "supervisor restarts preceding this process (from the "
            "resume record)"
        )
        families: list = [
            (name, "gauge", helps.get(name), [(None, gauges[name])])
            for name in sorted(gauges)
        ]
        if phases:
            families.append((
                "nanodiloco_phase_seconds", "gauge",
                "last round's host-side phase budget",
                [(f'phase="{ph}"', phases[ph]) for ph in sorted(phases)],
            ))
        # resilience counters: alarms/injected faults by kind, IO retries
        # by op, checkpoint resumes — the scrapeable fault timeline
        for name, help_text, label, by in (
            ("nanodiloco_alarms", "watchdog alarms by kind", "kind", alarms),
            ("nanodiloco_faults", "injected faults fired, by kind", "kind",
             faults),
            ("nanodiloco_retries", "IO retry attempts, by operation", "op",
             retries),
        ):
            families.append((
                name, "counter", help_text,
                [(f'{label}="{k}"', by[k]) for k in sorted(by)]
                + [(None, sum(by.values()))],
            ))
        families.append(("nanodiloco_resumes", "counter", None,
                         [(None, resumes)]))
        families.append(("nanodiloco_outer_syncs", "counter", None,
                         [(None, syncs)]))
        families.append((
            "nanodiloco_wire_bytes", "counter",
            "cumulative per-worker outer-sync wire bytes",
            [(None, wire_total)],
        ))
        return render_exposition(families)

    def health(self) -> tuple[int, dict]:
        """(status code, document). Unhealthy (503) = stalled, crashed,
        or any ``nan_loss`` alarm on record; everything else — spikes,
        throughput dips, a finished run — reports 200 with the detail
        in the body."""
        if self._health_fn is None:
            return 200, {"state": "unknown", "healthy": True}
        try:
            doc = dict(self._health_fn())
        except Exception as e:  # a broken probe is itself unhealthy
            return 503, {"state": "error", "healthy": False, "error": str(e)}
        kinds = doc.get("alarm_kinds") or {}
        unhealthy = (
            doc.get("state") in ("stalled", "crashed")
            or kinds.get("nan_loss", 0) > 0
        )
        doc["healthy"] = not unhealthy
        return (503 if unhealthy else 200), doc


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() and abs(v) < 2**53 else repr(v)


def render_exposition(families) -> str:
    """OpenMetrics text from ``(name, type, help, samples)`` families,
    where ``samples`` is ``[(labels_or_None, value)]`` (labels as a
    pre-rendered ``key="value"`` string). Counters follow the spec's
    family-name / ``_total``-sample split; ``# EOF`` terminates the
    exposition (a truncated scrape must be detectable as truncated).
    Shared by the training telemetry endpoint above and the serving
    endpoint (nanodiloco_tpu/serve/server.py)."""
    lines: list[str] = []
    for name, mtype, help_text, samples in families:
        lines.append(f"# TYPE {name} {mtype}")
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        sample_name = name + "_total" if mtype == "counter" else name
        for labels, value in samples:
            if labels:
                lines.append(f"{sample_name}{{{labels}}} {_fmt(value)}")
            else:
                lines.append(f"{sample_name} {_fmt(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_metrics_text(text: str) -> dict[str, float]:
    """Parse an OpenMetrics exposition into ``{sample_name: value}``
    with the label set kept verbatim in the key (e.g.
    ``nanodiloco_alarms_total{kind="nan_loss"}``). The consumer half of
    the scrape loop (tests, chip_agenda's telemetry phase) — tolerant
    of unknown lines, strict about nothing."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out
