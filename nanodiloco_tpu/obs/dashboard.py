"""Offline metrics dashboard: one self-contained static HTML page.

The incident question "what did the fleet do" must be answerable with
NOTHING running — no collector, no replicas, no plotting stack, no
network. ``render_dashboard`` turns a series dict (from a collector
``--series-jsonl`` artifact, or synthesized from a serve stats JSONL)
into a single HTML file: unicode-sparkline tables for SLO burn, fleet
goodput, the device-second budget by program, cost per class, and a
capacity forecast, styled by an inline stylesheet. No scripts, no
external fetches — the artifact opens from disk years later.

Section routing is substring-based over the ``target:sample`` keys the
collector writes (``flatten_families`` naming: counters carry
``_total``, labels verbatim), so the page organizes any fleet's scrape
without a per-deployment config. The capacity forecast reuses the
collector's Theil-Sen ``slope``/``forecast_exhaustion`` by replaying
the samples through a throwaway ``SeriesStore`` — ONE trend estimator
in the repo, online and offline.
"""

from __future__ import annotations

import html
import json

from nanodiloco_tpu.obs.collector import SeriesStore, sparkline

Series = dict[str, list[tuple[float, float]]]

# (section title, blurb, substring matchers) — first match wins, so a
# key lands in exactly one section
_SECTIONS: list[tuple[str, str, tuple[str, ...]]] = [
    ("SLO burn",
     "multi-window burn-rate alerting state: alert counts, burning "
     "pairs, cumulative burn seconds",
     ("nanodiloco_slo_",)),
    ("Fleet goodput",
     "replica-seconds serving-and-ready over every tracked "
     "replica-second, plus fleet membership state",
     ("fleet_goodput_fraction", "fleet_replicas", "fleet_state_seconds",
      "goodput_fraction")),
    ("Device-second budget by program",
     "fence-timed dispatch and compile seconds per compiled program "
     "(kind:bucket:layout)",
     ("nanodiloco_device_seconds", "nanodiloco_compile_seconds",
      "fleet_replica_device_seconds")),
    ("Cost per class",
     "attributed device-seconds and KV block-seconds by SLO priority "
     "class — the billing rollup",
     ("serve_device_seconds", "serve_kv_block_seconds",
      "decode_interference_ratio")),
    ("Capacity forecast",
     "the supply/demand gauges the predictive autoscaler trends: KV "
     "headroom, queue depth, slots (Theil-Sen slope per second; "
     "exhaustion ETA when the trend crosses the bound)",
     ("kv_blocks_free", "serve_queue_depth", "serve_slots_busy",
      "forecast_")),
]

_STYLE = """
body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
       margin: 2rem auto; max-width: 72rem; color: #1a2330;
       background: #fafbfc; }
h1 { font-size: 1.4rem; border-bottom: 2px solid #2b6cb0;
     padding-bottom: .3rem; }
h2 { font-size: 1.05rem; margin-top: 2rem; color: #2b6cb0; }
p.blurb { color: #5a6675; font-size: .85rem; margin: .2rem 0 .6rem; }
table { border-collapse: collapse; width: 100%; font-size: .8rem; }
th, td { text-align: left; padding: .25rem .6rem;
         border-bottom: 1px solid #e3e8ee; }
th { color: #5a6675; font-weight: 600; }
td.spark { font-family: 'SF Mono', Menlo, Consolas, monospace;
           font-size: .9rem; color: #2b6cb0; letter-spacing: -1px;
           white-space: nowrap; }
td.num { font-variant-numeric: tabular-nums; white-space: nowrap; }
td.key { font-family: 'SF Mono', Menlo, Consolas, monospace;
         font-size: .75rem; word-break: break-all; }
p.empty { color: #8a94a3; font-style: italic; font-size: .85rem; }
footer { margin-top: 2.5rem; color: #8a94a3; font-size: .75rem;
         border-top: 1px solid #e3e8ee; padding-top: .5rem; }
"""


def _fmt(v: float | None) -> str:
    if v is None:
        return "—"
    return f"{v:.4g}"


def _section_rows(keys: list[str], series: Series, width: int) -> str:
    rows = []
    for key in keys:
        samples = series[key]
        vals = [v for _, v in samples]
        rows.append(
            "<tr>"
            f"<td class=key>{html.escape(key)}</td>"
            f"<td class=spark>{sparkline(vals, width=width)}</td>"
            f"<td class=num>{_fmt(min(vals))}</td>"
            f"<td class=num>{_fmt(max(vals))}</td>"
            f"<td class=num>{_fmt(vals[-1])}</td>"
            f"<td class=num>{len(vals)}</td>"
            "</tr>"
        )
    return "\n".join(rows)


def _forecast_rows(keys: list[str], series: Series) -> str:
    """Trend table for the capacity section: replay each series through
    a throwaway SeriesStore so the SAME Theil-Sen slope the live
    autoscaler acts on is what the offline page reports."""
    rows = []
    for key in keys:
        samples = series[key]
        store = SeriesStore(maxlen=max(2, len(samples)))
        for t, v in samples:
            store.add(key, t, v)
        t_last = samples[-1][0]
        window = max(1e-9, t_last - samples[0][0])
        slope = store.slope(key, window, t_last)
        eta = None
        if "free" in key or "slots" in key:
            eta = store.forecast_exhaustion(key, 0.0, window, t_last,
                                            kind="floor")
        slope_s = "—" if slope is None else f"{slope:+.4g}/s"
        eta_s = ("—" if eta is None
                 else ("now" if eta == 0.0 else f"{eta:.0f}s"))
        rows.append(
            "<tr>"
            f"<td class=key>{html.escape(key)}</td>"
            f"<td class=num>{_fmt(samples[-1][1])}</td>"
            f"<td class=num>{slope_s}</td>"
            f"<td class=num>{eta_s}</td>"
            "</tr>"
        )
    return "\n".join(rows)


def render_dashboard(series: Series, *, title: str = "nanodiloco fleet",
                     width: int = 60) -> str:
    """The page. Keys route to the first section whose substring
    matches; everything unmatched lands in a final "Other series"
    table so no scraped series silently vanishes from the artifact."""
    remaining = sorted(series)
    parts = [
        "<!DOCTYPE html>",
        "<html lang=\"en\"><head><meta charset=\"utf-8\">",
        f"<title>{html.escape(title)}</title>",
        f"<style>{_STYLE}</style>",
        "</head><body>",
        f"<h1>{html.escape(title)} — offline metrics dashboard</h1>",
    ]
    header = ("<tr><th>series</th><th>trend</th><th>min</th><th>max</th>"
              "<th>last</th><th>n</th></tr>")
    for sec_title, blurb, needles in _SECTIONS:
        matched = [k for k in remaining
                   if any(n in k for n in needles)]
        remaining = [k for k in remaining if k not in matched]
        parts.append(f"<h2>{html.escape(sec_title)}</h2>")
        parts.append(f"<p class=blurb>{html.escape(blurb)}</p>")
        if not matched:
            parts.append("<p class=empty>no matching series in this "
                         "artifact</p>")
            continue
        parts.append(f"<table>{header}"
                     f"{_section_rows(matched, series, width)}</table>")
        if sec_title == "Capacity forecast":
            parts.append(
                "<table><tr><th>series</th><th>last</th>"
                "<th>Theil-Sen slope</th><th>exhaustion ETA</th></tr>"
                f"{_forecast_rows(matched, series)}</table>"
            )
    if remaining:
        parts.append("<h2>Other series</h2>")
        parts.append("<p class=blurb>every remaining scraped series — "
                     "nothing in the artifact is dropped</p>")
        parts.append(f"<table>{header}"
                     f"{_section_rows(remaining, series, width)}</table>")
    n_samples = sum(len(v) for v in series.values())
    parts.append(
        f"<footer>{len(series)} series · {n_samples} samples · "
        "rendered fully offline by <code>nanodiloco_tpu report "
        "dashboard</code> — no scripts, no network</footer>"
    )
    parts.append("</body></html>")
    return "\n".join(parts)


def serve_stats_series(path: str) -> Series:
    """Synthesize a series dict from a serve stats JSONL (the
    ``--stats-jsonl`` artifact): each ``serve_stats`` record becomes
    one sample per scalar metric, keyed ``serve:<metric>`` in the same
    label syntax the collector writes, so ``render_dashboard`` routes
    them to the same sections a scraped fleet's series land in. Nested
    attribution dicts (devtime ledgers, per-class costs) expand into
    labeled keys. Records without ``t_unix`` (older JSONLs) use the
    record index as the time axis."""
    from nanodiloco_tpu.training.metrics import read_jsonl_records

    recs, _torn = read_jsonl_records(path)
    out: Series = {}

    def add(sample: str, t: float, v: float) -> None:
        out.setdefault(f"serve:{sample}", []).append((t, float(v)))

    idx = 0.0
    for r in recs:
        if not r.get("serve_stats"):
            continue
        t = float(r.get("t_unix", idx))
        idx += 1.0
        for k, v in r.items():
            if isinstance(v, bool) or k in ("serve_stats", "t_unix"):
                continue
            if isinstance(v, (int, float)):
                add(k, t, v)
        dt = r.get("devtime")
        if isinstance(dt, dict):
            for ledger, family in (
                ("device_seconds_by_program",
                 "nanodiloco_device_seconds_total"),
                ("compile_seconds_by_program",
                 "nanodiloco_compile_seconds_total"),
            ):
                for prog, v in (dt.get(ledger) or {}).items():
                    add(f'{family}{{program="{prog}"}}', t, v)
        for rec_key, family in (
            ("device_seconds_by_priority",
             "nanodiloco_serve_device_seconds_total"),
            ("kv_block_seconds_by_priority",
             "nanodiloco_serve_kv_block_seconds_total"),
        ):
            for prio, v in (r.get(rec_key) or {}).items():
                add(f'{family}{{priority="{prio}"}}', t, v)
        kv = r.get("kv_pool")
        if isinstance(kv, dict):
            for k in ("blocks_free", "blocks_used"):
                if isinstance(kv.get(k), (int, float)):
                    add(f"nanodiloco_kv_{k}", t, kv[k])
    return out


def load_dashboard_series(path: str) -> Series:
    """Auto-detect the artifact flavor: collector snapshot records
    (``{"series": target, "samples": {...}}``) read via
    ``read_series_jsonl``; serve stats records via
    ``serve_stats_series``. Raises ``ValueError`` when neither yields
    a single series (a typo'd path should fail loudly, not render an
    empty page)."""
    from nanodiloco_tpu.obs.collector import read_series_jsonl

    flavor = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                if rec.get("series") and isinstance(
                    rec.get("samples"), dict
                ):
                    flavor = "collector"
                    break
                if rec.get("serve_stats"):
                    flavor = "serve"
                    break
    if flavor == "collector":
        series = read_series_jsonl(path)
    elif flavor == "serve":
        series = serve_stats_series(path)
    else:
        raise ValueError(
            f"{path} holds neither collector series records nor "
            "serve_stats records"
        )
    if not series:
        raise ValueError(f"no usable series in {path}")
    return series
