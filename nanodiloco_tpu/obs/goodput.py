"""Goodput ledger: attribute EVERY wall-clock second of a run to a cause.

DiLoCo's whole premise is trading communication for wall-clock on
unreliable pools (arXiv:2311.08105), and production-scale training
reports (MegaScale, arXiv:2402.15627) converge on one metric for such
pools: *effective training time* — the fraction of elapsed wall-clock
that produced tokens, versus compile, checkpoint, sync residual,
stalls, and restart downtime. The repo already times individual phases
(tracer ``t_*`` totals, the async residual apply-wait, supervisor
restarts); this module PARTITIONS them: every second of a run lifetime
lands in exactly one cause of a closed set, the residual the phases
don't cover lands in ``other`` (never silently dropped), and seconds
that happened while the process did not even exist (the supervisor's
relaunch gap) are booked as ``restart_downtime`` — so a supervised
crash-loopy run reports one honest end-to-end goodput fraction and a
tokens-per-wall-clock-second that includes its restarts.

Accounting contract (the property the tests pin):

- ``sum(cause seconds) == elapsed wall-clock`` exactly, by
  construction: attributed phase seconds are clamped to the window they
  were observed in and the remainder is ``other`` (or a caller-chosen
  residual cause, e.g. ``stall`` for a watchdog-killed lifetime).
- causes never overlap: the tracer's depth-0 spans are disjoint by
  construction, and in async mode only the residual apply-wait is
  booked as ``outer_sync`` (the overlapped launch rides inside
  ``compute`` — that's the point of the overlap, and booking it twice
  would claim the hidden cost is still paid).
- the ledger is pure host-side observation: it never touches jax and
  cannot perturb the trajectory (smoke-gate-asserted).

Records: each round the train loop logs a ``{"goodput": {...}}`` JSONL
record that is the RUNNING ledger snapshot for this process lifetime
(cumulative cause seconds, elapsed, fraction, tokens). Snapshots rather
than deltas so a lifetime that CRASHES mid-run still has its last
snapshot on disk — ``stitch_goodput_records`` takes the last snapshot
of every lifetime in a (restart-appended) JSONL and folds them into one
run-level ledger.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Iterable

#: The closed cause set. ``compute`` is the only goodput cause — every
#: other bucket is badput an operator can act on.
CAUSES = (
    "compute",            # inner steps actually producing tokens
    "outer_sync",         # sync path / async residual apply-wait
    "compile_warmup",     # first-dispatch compiles + measure-comm probes
    "checkpoint",         # save path on the driver thread
    "data_wait",          # the loop blocked on batch assembly
    "eval",               # held-out eval + MoE probes
    "resume_restore",     # checkpoint restore at startup
    "stall",              # watchdog-attributed dead time
    "straggler_wait",     # measured wait on a slow worker (elastic DiLoCo)
    "restart_downtime",   # supervisor relaunch gap (no process existed)
    "other",              # startup/logging/unattributed residual
)

#: The closed cause set for FLEET goodput (the serving twin of
#: ``CAUSES``): every replica-second the fleet router tracks lands in
#: exactly one of these buckets (``fleet/router.py`` imports this tuple
#: as its bucket names — one source of truth, so the autoscaler cannot
#: invent a state the accounting silently drops). ``serving_ready`` is
#: the only goodput bucket; ``scaling_up``/``scaling_down`` book the
#: autoscaler's transition seconds explicitly (MegaScale's every-
#: second-accounted discipline extended to elastic capacity).
FLEET_STATE_CAUSES = (
    "serving_ready",      # probed ready: usable serving capacity
    "serving_unready",    # alive but failing probes (compile, overload)
    "draining",           # admission stopped for a weight push
    "ejected",            # ejected after repeated probe failures
    "scaling_up",         # launched by the autoscaler, not yet ready
    "scaling_down",       # retiring: drain -> remove in progress
    "breaker_open",       # circuit breaker open/half-open: gray failure
)

#: tracer depth-0 span name -> cause. ``t_``-prefixed JSONL keys map
#: through the same table (``observe_phases`` strips the prefix).
PHASE_CAUSE = {
    "inner": "compute",
    "sync": "outer_sync",
    "ckpt": "checkpoint",
    "data": "data_wait",
    "eval": "eval",
    "restore": "resume_restore",
    "comm_probe": "compile_warmup",  # extra compile + throwaway rounds
    # the per-round straggler wait the train loop splits OUT of the
    # inner span (t_straggler in the round budget): healthy workers'
    # seconds spent on the slowest island, attributed — never silently
    # inflating compute or outer_sync
    "straggler": "straggler_wait",
    "cost_analysis": "other",
    "log": "other",
}


class GoodputLedger:
    """Per-process-lifetime wall-clock partition. ``clock`` is a
    monotonic seconds source (tests inject a fake); ``wall`` stamps
    snapshots with absolute time. ``lifetime`` is the supervisor's
    restart ordinal, the key ``stitch_goodput_records`` groups by."""

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        wall: Callable[[], float] = time.time,
        lifetime: int = 0,
    ) -> None:
        self._clock = clock
        self._wall = wall
        self.lifetime = int(lifetime)
        # stamped into every snapshot: the stitcher's discriminator
        # between two PROCESSES that share a lifetime ordinal (the
        # supervisor's restart count resets per invocation)
        self._pid = os.getpid()
        self._t0: float | None = None
        self._attributed: dict[str, float] = {c: 0.0 for c in CAUSES}
        # seconds that happened OUTSIDE this process's clock (the
        # supervisor's relaunch gap): they extend elapsed as well as
        # their cause, so the partition includes time no process saw
        self._external = 0.0
        self._tokens = 0

    def start(self) -> "GoodputLedger":
        """Open the ledger window (idempotent — the first call wins, so
        'as early in the process as possible' is safe to call twice)."""
        if self._t0 is None:
            self._t0 = self._clock()
        return self

    def note(self, cause: str, seconds: float) -> None:
        """Attribute ``seconds`` of this lifetime's elapsed wall-clock
        to ``cause``."""
        if cause not in self._attributed:
            raise ValueError(f"unknown goodput cause {cause!r}; use one of {CAUSES}")
        self._attributed[cause] += max(0.0, float(seconds))

    def book_external(self, cause: str, seconds: float) -> None:
        """Attribute seconds that elapsed while THIS process did not
        exist (the supervisor's relaunch gap, handed down via the
        downtime env var): they extend the ledger's elapsed total too —
        downtime is part of the run's wall-clock even though no clock of
        ours was running."""
        s = max(0.0, float(seconds))
        self.note(cause, s)
        self._external += s

    def observe_phases(
        self, phases: dict[str, float], warmup: bool = False
    ) -> None:
        """Fold one round's phase budget into the ledger. Accepts both
        raw tracer names (``inner``) and JSONL keys (``t_inner``);
        unknown phases land in ``other`` — a new span name must never
        silently vanish from the partition. ``warmup=True`` routes
        compute-destined seconds to ``compile_warmup`` instead: the
        first dispatch of each program carries its XLA compile, and
        calling that round "compute" would flatter the fraction."""
        for key, v in phases.items():
            if not isinstance(v, (int, float)) or v is None:
                continue
            name = key[2:] if key.startswith("t_") else key
            cause = PHASE_CAUSE.get(name, "other")
            if warmup and cause == "compute":
                cause = "compile_warmup"
            self.note(cause, v)

    def add_tokens(self, n: int) -> None:
        """Tokens produced this lifetime (the numerator of
        tokens-per-wall-clock-second-including-restarts)."""
        self._tokens += int(n)

    # -- snapshots -----------------------------------------------------------

    def elapsed_s(self) -> float:
        self.start()
        return (self._clock() - self._t0) + self._external

    def snapshot(
        self, final: bool = False, residual_cause: str = "other"
    ) -> dict[str, Any]:
        """The running ledger record (cumulative for this lifetime):
        per-cause seconds with the unattributed residual folded into
        ``residual_cause`` (``other`` normally; a watchdog-stall exit
        books its dead tail as ``stall``), elapsed, goodput fraction,
        tokens. The returned causes PARTITION elapsed exactly. When
        attribution overshoots elapsed (sub-ms clock skew between the
        tracer's clock and ours), causes are scaled down to fit — the
        partition property holds in both directions."""
        elapsed = self.elapsed_s()
        causes = {c: self._attributed[c] for c in CAUSES}
        attributed = sum(causes.values())
        residual = elapsed - attributed
        if residual >= 0:
            causes[residual_cause] += residual
        elif attributed > 0:
            scale = elapsed / attributed
            causes = {c: v * scale for c, v in causes.items()}
        rec: dict[str, Any] = {
            "lifetime": self.lifetime,
            "pid": self._pid,
            "elapsed_s": round(elapsed, 6),
            "tokens": self._tokens,
            "t_unix": round(self._wall(), 3),
        }
        for c in CAUSES:
            rec[f"{c}_s"] = round(causes[c], 6)
        rec["goodput_fraction"] = round(
            causes["compute"] / elapsed, 6
        ) if elapsed > 0 else None
        if elapsed > 0:
            rec["tokens_per_wall_s"] = round(self._tokens / elapsed, 3)
        if final:
            rec["final"] = True
        return rec


def stitch_goodput_records(records: Iterable[dict]) -> dict[str, Any] | None:
    """Fold the ``goodput`` snapshots of a (restart-appended) JSONL into
    ONE run-level ledger: the LAST snapshot of each process lifetime
    stands for that lifetime (snapshots are cumulative; a crashed
    lifetime's last snapshot is everything it managed to record), cause
    seconds and tokens sum across lifetimes, and the merged fraction is
    compute / total elapsed — restarts included, because each resumed
    lifetime booked its relaunch gap as ``restart_downtime``.

    Lifetimes are segmented by JSONL ORDER, not keyed by the ordinal
    alone: the supervisor's restart ordinal resets to 0 on every
    ``supervise`` invocation, so a run supervised twice appends two
    ``lifetime: 0`` series to one file — a new segment starts whenever
    the ordinal changes, the writing PROCESS changes (the ``pid`` each
    snapshot carries — the only discriminator when a fresh process's
    first compile-heavy round makes its elapsed overtake the previous
    invocation's), or — for pid-less older records — the cumulative
    ``elapsed_s`` goes backwards. Keying by ordinal would silently drop
    the first invocation's seconds from the "honest end-to-end" number.
    Returns None when no snapshot exists (an older JSONL — consumers
    must tolerate runs that predate the ledger)."""
    segments: list[dict] = []
    cur: dict | None = None
    for r in records:
        g = r.get("goodput") if isinstance(r, dict) else None
        if not (isinstance(g, dict)
                and isinstance(g.get("elapsed_s"), (int, float))):
            continue
        try:
            life = int(g.get("lifetime", 0))
        except (TypeError, ValueError):
            life = 0
        pid = g.get("pid")
        same_segment = (
            cur is not None
            and life == cur["_life"]
            # cumulative elapsed must be monotone within one process...
            and float(g["elapsed_s"]) >= cur["elapsed_s"]
            # ...and a pid change splits even when a fresh process's
            # compile-heavy first round overtakes the previous
            # invocation's elapsed (pid-less older records keep the
            # elapsed heuristic alone)
            and not (
                pid is not None and cur.get("pid") is not None
                and pid != cur.get("pid")
            )
        )
        if same_segment:
            segments[-1] = cur = {**g, "_life": life}
        else:
            cur = {**g, "_life": life}
            segments.append(cur)
    if not segments:
        return None
    causes = {c: 0.0 for c in CAUSES}
    elapsed = 0.0
    tokens = 0
    for g in segments:
        elapsed += float(g["elapsed_s"])
        tokens += int(g.get("tokens") or 0)
        for c in CAUSES:
            v = g.get(f"{c}_s")
            if isinstance(v, (int, float)):
                causes[c] += float(v)
    out: dict[str, Any] = {
        "lifetimes": len(segments),
        "elapsed_s": round(elapsed, 6),
        "tokens": tokens,
    }
    for c in CAUSES:
        out[f"{c}_s"] = round(causes[c], 6)
    out["goodput_fraction"] = (
        round(causes["compute"] / elapsed, 6) if elapsed > 0 else None
    )
    if elapsed > 0:
        out["tokens_per_wall_s"] = round(tokens / elapsed, 3)
    badput = {c: causes[c] for c in CAUSES if c != "compute"}
    top = max(badput, key=lambda c: badput[c]) if any(badput.values()) else None
    out["badput_top_cause"] = top
    return out
