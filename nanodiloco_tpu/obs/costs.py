"""XLA cost analytics: what the compiler says a program costs.

PERF.md's MFU table is hand-derived — a formula multiplied by a
measured tokens/sec. That formula (``train_flops_per_token``, moved
here from bench.py so there is ONE implementation) is an analytic
claim about the model; XLA's own cost model is an analytic claim about
the PROGRAM actually lowered (fusion choices, remat recompute, the
one-hot MoE dispatch einsums — everything the hand formula has to
approximate). Capturing ``cost_analysis()`` from the fused round
program at lowering time and logging it ONCE into the run JSONL turns
"measured MFU vs what the program should cost" into a computed,
regression-gateable artifact (``report cost``, ``mfu_analytic`` in
``report compare``).

Scope honesty: the numbers come from ``Lowered.cost_analysis()`` — the
pre-optimization HLO walked by XLA's cost model. Lowering is a trace +
StableHLO emission (seconds, host-only); it does NOT pay a second XLA
compile, and matmul/attention FLOPs — the MFU numerator — are
invariant under the optimization passes that follow. ``bytes accessed``
is the cost model's pre-fusion estimate and overstates what the
optimized program touches; it is recorded for trend tracking, not as
an HBM-traffic truth.

Loop caveat (measured, load-bearing): XLA's cost model counts each
``while``/``scan`` BODY exactly once, whatever the trip count — in
both the pre-optimization (``Lowered``) and compiled analyses. This
codebase scans over layers, CE chunks, grad-accum microbatches, and
the round's H steps, so the dispatched executable's billed FLOPs are
one layer + one chunk + one microbatch worth of compute plus the tails
— NOT normalizable per token. The cost record therefore carries TWO
views: the raw ``flops_billed``/``bytes_accessed_billed`` of the real
executable (trend tracking: a new fusion or an extra collective moves
them), and a per-token ``flops`` from a PROBE lowering of one
microbatch's fwd+bwd with every scan force-unrolled
(``unrolled_scans``), where the cost model genuinely bills all L
layers and every CE chunk. The probe is lowering-only (abstract
inputs, never compiled or executed).

No jax import at module level (obs/ stays importable host-side
everywhere); functions that need the backend import it lazily.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any


@contextmanager
def unrolled_scans():
    """Force every ``jax.lax.scan`` lowered inside this context to
    fully unroll — so a cost-analysis probe bills ALL loop iterations
    instead of XLA's body-counted-once default (module docstring).
    Lowering-only tool: an unrolled 32-layer stack is a big StableHLO
    module but never compiles or runs. Patches the module attribute the
    model code calls (``jax.lax.scan``), restores it on exit; callers
    hold no other tracing in flight (the train loop probes once, before
    round 1's dispatch)."""
    import jax

    orig = jax.lax.scan

    def scan(f, init, xs=None, length=None, **kwargs):
        kwargs["unroll"] = True
        return orig(f, init, xs=xs, length=length, **kwargs)

    jax.lax.scan = scan
    try:
        yield
    finally:
        jax.lax.scan = orig

# bf16 peak TFLOP/s per chip by device kind substring (first match
# wins). Override with BENCH_PEAK_TFLOPS when the kind string is
# missing or wrong. Single source of truth — bench.py delegates here.
PEAK_TFLOPS_BY_KIND = [
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5", 197.0),   # v5e / "v5 lite"
    ("v4", 275.0),
    ("v3", 123.0),
]


def detect_peak_tflops() -> tuple[float | None, str]:
    """(bf16 peak TFLOP/s per chip or None, device kind string) for the
    current backend. ``BENCH_PEAK_TFLOPS`` overrides the table; an
    unknown kind (CPU included) yields None — consumers must report
    "no peak known", never fake an MFU against a made-up ceiling."""
    import jax

    kind = jax.devices()[0].device_kind
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env), kind
    low = kind.lower()
    for sub, peak in PEAK_TFLOPS_BY_KIND:
        if sub in low:
            return peak, kind
    return None, kind


def train_flops_per_token(cfg, seq: int, moe_tokens: int | None = None) -> float:
    """Matmul FLOPs per trained token, fwd+bwd (3x fwd): 6 x matmul
    params (embedding lookup excluded, lm_head included) plus attention
    scores/values 12*L*S*d (non-causal convention). For MoE, executed
    FLOPs means (a) the expert FFN counts the slots actually COMPUTED
    (dense dispatch runs E x C = k x capacity_factor slot-passes per
    token), not all E experts' parameters, and (b) the dense
    dispatch/combine one-hot einsums are counted too — they are real
    MXU matmuls of the same order as the FFN at bench shapes, O(T) per
    token like attention (``moe_tokens`` = the T = batch x seq the
    [T, E, C] routing tensors span; defaults to ``seq``)."""
    matmul_params = cfg.num_params() - cfg.vocab_size * cfg.hidden_size
    out = 12.0 * cfg.num_hidden_layers * seq * cfg.hidden_size
    if cfg.num_experts:
        d, f = cfg.hidden_size, cfg.intermediate_size
        kcf = cfg.num_experts_per_tok * cfg.expert_capacity_factor
        all_experts = 3 * cfg.num_experts * d * f
        matmul_params += cfg.num_hidden_layers * (3 * d * f * kcf - all_experts)
        t = moe_tokens if moe_tokens is not None else seq
        # dispatch ('tec,td->ecd') + combine ('tec,ecd->td'): E*C*d MACs
        # per token each, E*C ~= kcf*T -> 2 einsums x 3 (fwd+bwd) x
        # 2 FLOPs/MAC
        out += 12.0 * cfg.num_hidden_layers * kcf * t * d
    return 6.0 * matmul_params + out


def lowered_cost(lowered) -> dict[str, float] | None:
    """Normalize ``jax.stages.Lowered.cost_analysis()`` across jax
    versions (a dict on some releases, a one-element list of dicts on
    others) into ``{"flops", "bytes_accessed"}``. None when the
    backend's cost model reports nothing usable — callers must treat
    that as "no analytics", never as zero cost."""
    try:
        ca = lowered.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out: dict[str, float] = {}
    flops = ca.get("flops")
    if isinstance(flops, (int, float)) and flops > 0:
        out["flops"] = float(flops)
    ba = ca.get("bytes accessed")
    if isinstance(ba, (int, float)) and ba > 0:
        out["bytes_accessed"] = float(ba)
    return out or None


def build_cost_record(
    *,
    program: str,
    billed: dict[str, float] | None = None,
    probe: dict[str, float] | None = None,
    probe_tokens: int = 0,
    num_devices: int = 1,
    model_cfg=None,
    seq: int | None = None,
    moe_tokens: int | None = None,
) -> dict[str, Any]:
    """The one-time ``cost_analysis`` JSONL record: the raw XLA numbers
    plus everything a later ``report cost`` needs without re-deriving
    state — per-token normalization, the hand formula captured at the
    SAME shapes (fit_vocab shrinks included), and the chip peak known
    at capture time (a JSONL scraped off a pod must not need the chip
    to compute MFU).

    ``billed`` is the dispatched executable's own analysis (loop bodies
    counted once — module docstring); ``probe`` is the unrolled
    one-microbatch fwd+bwd over ``probe_tokens`` tokens, the basis for
    ``flops_per_token`` and therefore analytic MFU."""
    rec: dict[str, Any] = {
        "program": program,
        "num_devices": int(num_devices),
    }
    if billed:
        if "flops" in billed:
            rec["flops_billed"] = billed["flops"]
        if "bytes_accessed" in billed:
            rec["bytes_accessed_billed"] = billed["bytes_accessed"]
    if probe and probe_tokens > 0 and "flops" in probe:
        rec["flops"] = probe["flops"]
        rec["tokens_counted"] = int(probe_tokens)
        rec["flops_per_token"] = probe["flops"] / probe_tokens
    if model_cfg is not None and seq:
        rec["flops_per_token_hand"] = train_flops_per_token(
            model_cfg, seq, moe_tokens=moe_tokens
        )
    try:
        peak, kind = detect_peak_tflops()
    except Exception:
        peak, kind = None, "unknown"
    if peak:
        rec["peak_tflops"] = peak
    rec["device_kind"] = kind
    return rec


def analytic_mfu(
    cost: dict[str, Any], tokens_per_sec: float
) -> float | None:
    """Measured global tokens/sec x the program's analytic FLOPs/token,
    against the captured per-chip peak x device count. None when the
    record lacks a peak (CPU mesh, unknown kind) — no fake ceilings."""
    fpt = cost.get("flops_per_token")
    peak = cost.get("peak_tflops")
    n_dev = cost.get("num_devices") or 1
    if not (fpt and peak and tokens_per_sec and tokens_per_sec > 0):
        return None
    return tokens_per_sec * fpt / (n_dev * peak * 1e12)
