"""Fleet capacity model: per-replica series -> demand/supply forecasts.

The collector (``obs/collector.py``) holds per-replica time series; the
autoscaler (``fleet/autoscaler.py``) needs ONE fleet-level answer:
"is demand trending past supply, and how many seconds until something
runs out?". ``CapacityModel`` is that join, built entirely from the
SeriesStore's forecasting queries (``slope``/``forecast_exhaustion``/
``rate``) — never raw point gauges, which is the whole point: a point
gauge says the fleet is fine right up until the tick it is not
(MegaScale's operability premise, arXiv:2402.15627).

Every estimate carries a CONFIDENCE HORIZON: the span of samples that
backs it. A forecast farther out than ``beyond_factor`` x that span is
extrapolating past its evidence and is dropped (reported as "not
imminent"), and an estimate backed by less than ``min_horizon_s`` of
data is flagged not-confident — the autoscaler treats both as "do
nothing yet", so a replica that just booted (two samples, wild slope)
cannot trigger a phantom scale event.

Stdlib only, no device work; everything is testable with a scripted
SeriesStore and a fake clock.
"""

from __future__ import annotations

import dataclasses

from nanodiloco_tpu.obs.collector import SeriesStore

# the serve-replica sample names the model joins over (the exact names
# serve/server.py render_metrics emits; collector keys are
# "{target}:{sample}")
QUEUE_DEPTH_SAMPLE = "nanodiloco_serve_queue_depth"
KV_FREE_SAMPLE = "nanodiloco_kv_blocks_free"
SLOTS_TOTAL_SAMPLE = "nanodiloco_serve_slots_total"
REQUESTS_TOTAL_SAMPLE = "nanodiloco_serve_requests_total"


@dataclasses.dataclass(frozen=True)
class CapacityEstimate:
    """One fleet-level capacity reading at time ``at``.

    Demand: ``queue_depth``/``queue_slope`` (fleet-summed waiting
    requests and their per-second trend) and ``request_rate``
    (completed requests/s). Supply: ``kv_blocks_free`` (fleet-summed
    headroom). Forecasts: ``kv_exhaustion_s`` (seconds until the FIRST
    replica's KV pool hits 0 — min over replicas, because the fleet
    degrades when one replica saturates, not when the average does) and
    ``queue_exhaustion_s`` (seconds until the first replica's queue
    depth crosses its slot capacity). ``horizon_s`` is the sample span
    backing the estimate; ``confident`` is False until that span
    reaches the model's ``min_horizon_s``."""

    at: float
    replicas: int
    queue_depth: float | None
    queue_slope: float | None
    request_rate: float | None
    kv_blocks_free: float | None
    kv_exhaustion_s: float | None
    queue_exhaustion_s: float | None
    horizon_s: float
    confident: bool

    def exhaustion_s(self) -> float | None:
        """The nearest credible exhaustion across resources (None =
        nothing forecast to run out)."""
        etas = [e for e in (self.kv_exhaustion_s, self.queue_exhaustion_s)
                if e is not None]
        return min(etas) if etas else None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class CapacityModel:
    """Turn a SeriesStore of per-replica serve metrics into fleet-level
    ``CapacityEstimate``s.

    ``targets`` names the replicas to join over; by default they are
    DISCOVERED from the store (every target that has ever reported a
    queue-depth sample), so a fleet the autoscaler itself grows is
    picked up without re-plumbing. ``window_s`` bounds every trend
    query; ``min_horizon_s`` is the minimum backing span before
    ``confident`` flips True; forecasts beyond ``beyond_factor`` x the
    backing span are dropped as extrapolation."""

    def __init__(
        self,
        store: SeriesStore,
        *,
        targets: list[str] | None = None,
        window_s: float = 60.0,
        min_horizon_s: float = 5.0,
        beyond_factor: float = 10.0,
    ) -> None:
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0; got {window_s}")
        if beyond_factor <= 0:
            raise ValueError(
                f"beyond_factor must be > 0; got {beyond_factor}"
            )
        self.store = store
        self._targets = list(targets) if targets is not None else None
        self.window_s = float(window_s)
        self.min_horizon_s = float(min_horizon_s)
        self.beyond_factor = float(beyond_factor)
        self._excluded: frozenset[str] = frozenset()

    def set_excluded(self, names) -> None:
        """Replicas to leave out of the supply join (e.g. circuit-breaker
        open: still scraping, but not credible capacity)."""
        self._excluded = frozenset(str(n) for n in names)

    def set_targets(self, names) -> None:
        """Pin the replica set the model joins over, replacing
        discovery. The disaggregated autoscaler (fleet/disagg.py) calls
        this every tick with ONE TIER's usable replicas
        (``FleetRouter.tier_capacity_names``), so a prefill replica's
        queue and KV headroom never count toward decode capacity — each
        tier's model sees only its own supply."""
        self._targets = [str(n) for n in names]

    def targets(self) -> list[str]:
        if self._targets is not None:
            names = list(self._targets)
        else:
            suffix = f":{QUEUE_DEPTH_SAMPLE}"
            names = sorted(
                k[: -len(suffix)]
                for k in self.store.keys()
                if k.endswith(suffix) and ":" not in k[: -len(suffix)]
            )
        if self._excluded:
            names = [n for n in names if n not in self._excluded]
        return names

    def _span(self, key: str, now: float) -> float:
        samples = self.store.window(key, now - self.window_s, now)
        if len(samples) < 2:
            return 0.0
        return samples[-1][0] - samples[0][0]

    def _credible(self, eta: float | None, horizon: float) -> float | None:
        """Drop forecasts that extrapolate past their evidence."""
        if eta is None or horizon <= 0:
            return None
        return eta if eta <= self.beyond_factor * horizon else None

    def estimate(self, now: float) -> CapacityEstimate:
        store = self.store
        targets = self.targets()
        q_depth_sum: float | None = None
        q_slope_sum: float | None = None
        rate_sum: float | None = None
        kv_free_sum: float | None = None
        kv_etas: list[float] = []
        q_etas: list[float] = []
        spans: list[float] = []
        fresh = 0
        for t in targets:
            qk = f"{t}:{QUEUE_DEPTH_SAMPLE}"
            last = store.latest(qk)
            if last is None or last[0] < now - self.window_s:
                continue  # stale/retired replica: not part of supply
            fresh += 1
            span = self._span(qk, now)
            spans.append(span)
            q_depth_sum = (q_depth_sum or 0.0) + last[1]
            qs = store.slope(qk, self.window_s, now)
            if qs is not None:
                q_slope_sum = (q_slope_sum or 0.0) + qs
            rr = store.rate(
                f"{t}:{REQUESTS_TOTAL_SAMPLE}", self.window_s, now
            )
            if rr is not None:
                rate_sum = (rate_sum or 0.0) + rr
            kvk = f"{t}:{KV_FREE_SAMPLE}"
            kv_last = store.latest(kvk)
            if kv_last is not None and kv_last[0] >= now - self.window_s:
                kv_free_sum = (kv_free_sum or 0.0) + kv_last[1]
                eta = self._credible(
                    store.forecast_exhaustion(
                        kvk, 0.0, self.window_s, now, kind="floor"
                    ),
                    self._span(kvk, now),
                )
                if eta is not None:
                    kv_etas.append(eta)
            slots = store.latest(f"{t}:{SLOTS_TOTAL_SAMPLE}")
            if slots is not None and slots[1] > 0:
                eta = self._credible(
                    store.forecast_exhaustion(
                        qk, slots[1], self.window_s, now, kind="ceiling"
                    ),
                    span,
                )
                if eta is not None:
                    q_etas.append(eta)
        horizon = min(spans) if spans else 0.0
        return CapacityEstimate(
            at=now,
            replicas=fresh,
            queue_depth=q_depth_sum,
            queue_slope=q_slope_sum,
            request_rate=rate_sum,
            kv_blocks_free=kv_free_sum,
            kv_exhaustion_s=min(kv_etas) if kv_etas else None,
            queue_exhaustion_s=min(q_etas) if q_etas else None,
            horizon_s=horizon,
            confident=bool(spans) and horizon >= self.min_horizon_s,
        )
