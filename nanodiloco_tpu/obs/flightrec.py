"""Crash flight recorder: a bounded black box that survives the crash.

The tracer's Chrome export and the metrics JSONL answer "what happened"
for runs that ENDED politely — but the runs where the timeline matters
most (a wedged chip, a crash loop, an engine thread dying mid-serve)
are exactly the ones that never reach a clean exporter. This module
keeps a bounded ring buffer of recent events (depth-0 spans,
heartbeats, alarms, JSONL records, serve completions) and dumps it
ATOMICALLY to ``<log_dir>/<run>-blackbox.json`` the moment something
fatal happens:

- a fatal watchdog alarm (stall / nan_loss) — ``obs/watchdog.py``;
- an unhandled exception escaping ``train()``;
- a fatal signal (faulthandler-adjacent best effort: SIGABRT/SIGBUS/
  SIGSEGV/SIGFPE — a hosed C stack may still not reach Python, but the
  cases that do get their dump);
- an injected hard-crash fault (``resilience/faults.fire_crash`` dumps
  BEFORE ``os._exit`` — the black box must record the crash that
  skipped every other teardown);
- the serve engine loop dying (``serve/server.py``).

The supervisor attaches the newest dump's path to its ``crash`` event
(``resilience/supervisor.py``), and ``report blackbox`` renders the
last-N event timeline.

Like the tracer, the recorder is installed process-globally so feeding
it is non-invasive: ``record_event`` is a no-op (one ``is None`` check)
until something installs a recorder, so library call sites never need
an ``if recording:`` guard.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from collections import deque
from typing import Any, Callable

#: Default ring capacity. ~512 events is minutes of context at round
#: cadence and a few KB on disk — a black box, not a second trace.
DEFAULT_CAPACITY = 512

#: Signals worth a best-effort dump. SIGTERM/SIGINT are NOT here: those
#: are the preemption path, owned by the train loop's graceful-stop
#: latch, and a dump would misreport a clean preempt as a crash.
FATAL_SIGNALS = tuple(
    s for s in ("SIGABRT", "SIGBUS", "SIGSEGV", "SIGFPE")
    if hasattr(signal, s)
)


class FlightRecorder:
    """Bounded, thread-safe ring of recent events with an atomic dump.

    ``clock``/``wall`` are injectable (tests drive the timeline).
    ``dump_path`` may be set at construction or later (the train loop
    only knows the run name after the logger resolves it)."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        dump_path: str | None = None,
        wall: Callable[[], float] = time.time,
    ) -> None:
        self._events: deque[dict[str, Any]] = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._wall = wall
        self.dump_path = dump_path
        self._dumped: str | None = None  # last dump reason (once is enough)
        self._dropped = 0

    def record(self, kind: str, /, **data: Any) -> None:
        # positional-only ``kind``: event data regularly carries its own
        # "kind" key (watchdog alarms, JSONL records) and must not
        # collide with the event's type
        ev = {"kind": str(kind), "t_unix": round(self._wall(), 3)}
        if data:
            ev["data"] = data
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)

    def events(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def dump(self, reason: str, path: str | None = None) -> str | None:
        """Write the black box atomically (tmp+rename — a crash mid-dump
        must never leave a torn file where forensics expects JSON).
        Returns the written path, or None when no path is configured or
        the disk refuses (a full disk must not mask the real crash).
        Repeated dumps overwrite: the LAST fatal event wins, and the
        reasons accumulate in the document so a dump-then-die sequence
        stays visible."""
        path = path or self.dump_path
        if not path:
            return None
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
            prior = self._dumped
            self._dumped = reason
        doc = {
            "blackbox": True,
            "reason": reason,
            **({"prior_reason": prior} if prior else {}),
            "t_unix": round(self._wall(), 3),
            "pid": os.getpid(),
            **({"dropped_events": dropped} if dropped else {}),
            "events": events,
        }
        try:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            return None
        return path


# -- process-global installation (the tracer's non-invasive pattern) ---------

_current: FlightRecorder | None = None
_lock = threading.Lock()


def install(rec: FlightRecorder | None) -> FlightRecorder | None:
    """Install ``rec`` as the process-wide recorder (None uninstalls).
    Returns the PREVIOUS recorder so callers can restore it — the train
    loop does, keeping concurrent tests from leaking recorders."""
    global _current
    with _lock:
        prev = _current
        _current = rec
    return prev


def current() -> FlightRecorder | None:
    return _current


def record_event(kind: str, /, **data: Any) -> None:
    """Record on the current recorder; free no-op when none installed."""
    rec = _current
    if rec is not None:
        rec.record(kind, **data)


def dump_current(reason: str) -> str | None:
    """Dump the current recorder's ring; None when none installed (or
    no dump path configured)."""
    rec = _current
    return rec.dump(reason) if rec is not None else None


# -- fatal-signal arming (faulthandler-adjacent) ------------------------------

_prev_handlers: dict[int, Any] = {}


def arm_fatal_signals() -> None:
    """Best-effort dump on SIGABRT/SIGBUS/SIGSEGV/SIGFPE: the handler
    dumps the ring, restores the default disposition, and re-raises so
    the process still dies with the original signal (exit codes and
    core dumps must stay honest). Main-thread only (the OS contract);
    silently a no-op elsewhere or on exotic embeddings. Pair with
    ``disarm_fatal_signals`` at teardown — an embedding process (tests,
    a notebook) must get its handlers back."""
    if threading.current_thread() is not threading.main_thread():
        return

    def _handler(signum, frame):
        try:
            dump_current(f"signal:{signal.Signals(signum).name}")
        except Exception:
            pass
        try:
            signal.signal(signum, _prev_handlers.get(signum, signal.SIG_DFL))
        except (ValueError, OSError):
            pass
        os.kill(os.getpid(), signum)

    for name in FATAL_SIGNALS:
        sig = getattr(signal, name)
        try:
            prev = signal.signal(sig, _handler)
        except (ValueError, OSError, RuntimeError):
            continue
        _prev_handlers.setdefault(sig, prev)


def disarm_fatal_signals() -> None:
    if threading.current_thread() is not threading.main_thread():
        return
    while _prev_handlers:
        sig, prev = _prev_handlers.popitem()
        try:
            signal.signal(sig, prev)
        except (ValueError, OSError, RuntimeError):
            pass
