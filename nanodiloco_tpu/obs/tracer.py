"""Lightweight host-side span tracer with Chrome trace-event export.

``jax.profiler`` answers "what is the DEVICE doing" at enormous capture
cost (one round, XLA-internal viewer); this tracer answers the
operator's daily question — "where does each ROUND's wall-clock go,
host-side, for the whole run" — at the cost of two ``perf_counter``
calls per span. Spans nest via a per-thread stack, export as Chrome
trace-event JSON (``chrome://tracing`` / Perfetto open it directly, no
jax tooling needed), and aggregate into per-phase totals
(``t_data``/``t_inner``/``t_sync``/...) that the train loop folds into
every sync's JSONL record, so a metrics stream alone reconstructs the
round budget.

Usage::

    with trace_span("outer_sync"):
        ...                      # nested trace_span calls nest in the UI

    tracer = current_tracer()
    totals = tracer.phase_totals()   # {"outer_sync": 0.173, ...}, resets
    tracer.export_chrome("trace.json")

The module-level current tracer makes instrumentation non-invasive:
library code calls ``trace_span`` unconditionally; when nothing
installed a real tracer the spans are recorded on a process-wide
default whose memory is bounded (``max_events``, oldest dropped).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable

from nanodiloco_tpu.obs import flightrec


class SpanTracer:
    """Records nested host-side spans; thread-safe, clock-injectable.

    ``clock`` must be a monotonic seconds source (tests inject a fake).
    ``max_events`` bounds memory on long runs: a 10k-round run with ~8
    spans/round is ~80k events ≈ a few MB; beyond the cap the OLDEST
    events are dropped (the exported trace keeps the most recent
    window, which is the one an operator debugging a live run wants).
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        max_events: int = 500_000,
        process_index: int = 0,
        process_name: str | None = None,
    ) -> None:
        self._clock = clock
        self._max_events = max_events
        # which process of a multi-host pod this tracer records; carried
        # in the export's metadata so merge_chrome_traces can assign
        # stable pids (the train loop passes jax.process_index() — this
        # module itself stays jax-free)
        self.process_index = int(process_index)
        # display name for the Chrome process lane; default keeps the
        # training "nanodiloco rank{k}" convention. A serve-side tracer
        # names itself distinctly so a merged train+serve timeline shows
        # two labeled lanes instead of two anonymous rank0s.
        self.process_name = process_name or f"nanodiloco rank{self.process_index}"
        self._lock = threading.Lock()
        self._events: list[dict[str, Any]] = []
        self._dropped = 0
        # tid -> human thread name, recorded at span time so the export
        # can emit Chrome thread_name metadata (Perfetto then shows
        # main/prefetch/watchdog lanes instead of raw get_ident() ints)
        self._thread_names: dict[int, str] = {}
        self._local = threading.local()
        # wall-clock anchor: trace timestamps are perf_counter-relative;
        # recording the pairing at construction lets the export carry an
        # absolute start time in metadata
        self._t0 = self._clock()
        self._wall0 = time.time()
        # per-phase accumulation window (phase_totals resets it)
        self._totals: dict[str, float] = {}
        self._totals_depth0_t0: float | None = None

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextmanager
    def span(self, name: str, **args: Any):
        """Record one span around the enclosed block. Exceptions
        propagate; the span still closes (the trace must show the round
        that crashed, not lose it)."""
        stack = self._stack()
        depth = len(stack)
        t0 = self._clock()
        stack.append(name)
        try:
            yield self
        finally:
            stack.pop()
            t1 = self._clock()
            tid = threading.get_ident()
            ev = {
                "name": name,
                "t0": t0,
                "dur": t1 - t0,
                "depth": depth,
                "tid": tid,
            }
            if args:
                ev["args"] = args
            with self._lock:
                if tid not in self._thread_names:
                    self._thread_names[tid] = threading.current_thread().name
                self._events.append(ev)
                if len(self._events) > self._max_events:
                    drop = len(self._events) - self._max_events
                    del self._events[:drop]
                    self._dropped += drop
                if depth == 0:
                    self._totals[name] = self._totals.get(name, 0.0) + (t1 - t0)
            if depth == 0:
                # black-box feed (obs/flightrec): the crash dump's last-N
                # timeline should show which phases ran up to the fatal
                # moment. One is-None check when no recorder is installed.
                flightrec.record_event("span", name=name, s=round(t1 - t0, 6))

    def record_span(self, name: str, t0: float, t1: float, **args: Any) -> None:
        """Record an ALREADY-TIMED span: ``t0``/``t1`` are values of
        THIS tracer's own clock, captured by the caller (the serve
        scheduler times request phases — queued/prefill/decode — with
        its injectable clock and reports them here after the fact; a
        context manager cannot wrap a wait that started on another
        thread). The caller must construct the tracer with the SAME
        clock it timestamps with, or the lanes won't line up. Recorded
        at depth 0, so serve phases aggregate into ``phase_totals``
        like the train loop's spans do."""
        if self._max_events <= 0:
            return
        tid = threading.get_ident()
        ev = {
            "name": name,
            "t0": float(t0),
            "dur": max(0.0, float(t1) - float(t0)),
            "depth": 0,
            "tid": tid,
        }
        if args:
            ev["args"] = args
        with self._lock:
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            self._events.append(ev)
            if len(self._events) > self._max_events:
                drop = len(self._events) - self._max_events
                del self._events[:drop]
                self._dropped += drop
            self._totals[name] = self._totals.get(name, 0.0) + ev["dur"]

    def phase_totals(self, reset: bool = True) -> dict[str, float]:
        """Seconds per DEPTH-0 span name since the last reset — the
        per-round phase budget. Only top-level spans count, so nested
        detail spans never double-bill their parent phase."""
        with self._lock:
            out = dict(self._totals)
            if reset:
                self._totals = {}
        return out

    @property
    def events(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def to_chrome(self) -> dict[str, Any]:
        """Chrome trace-event JSON object (the ``{"traceEvents": [...]}``
        form). Complete ("X") events; nesting is implied by containment
        on the same tid, which Perfetto renders as a flame graph.
        Metadata ("M") events name the process (``rank{k}``) and each
        thread, so the timeline shows ``main``/``prefetch`` lanes, not
        raw thread-id integers."""
        pid = os.getpid()
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
            thread_names = dict(self._thread_names)
        tev: list[dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": self.process_name},
            }
        ]
        for tid, tname in sorted(thread_names.items()):
            tev.append({
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": tname},
            })
        tev += [
            {
                "name": e["name"],
                "ph": "X",
                "ts": (e["t0"] - self._t0) * 1e6,   # microseconds
                "dur": e["dur"] * 1e6,
                "pid": pid,
                "tid": e["tid"],
                **({"args": e["args"]} if "args" in e else {}),
            }
            for e in events
        ]
        return {
            "traceEvents": tev,
            "displayTimeUnit": "ms",
            "otherData": {
                "tracer": "nanodiloco_tpu.obs",
                "wall_start_unix": self._wall0,
                "process_index": self.process_index,
                **({"dropped_events": dropped} if dropped else {}),
            },
        }

    def export_chrome(self, path: str) -> str:
        """Write the Chrome trace JSON to ``path`` (atomic: tmp+rename,
        so a crash mid-write never leaves a torn file where an operator
        expects a trace). Returns the path."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome(), f)
        os.replace(tmp, path)
        return path


class _NullTracer(SpanTracer):
    """Default when nothing installed a tracer: records nothing — zero
    overhead beyond the context-manager call, and library code never
    needs an ``if tracing:`` guard."""

    def __init__(self) -> None:
        super().__init__(max_events=0)

    @contextmanager
    def span(self, name: str, **args: Any):
        yield self

    def phase_totals(self, reset: bool = True) -> dict[str, float]:
        return {}


_null = _NullTracer()
_current: SpanTracer = _null
_current_lock = threading.Lock()


def set_tracer(tracer: SpanTracer | None) -> SpanTracer:
    """Install ``tracer`` as the process-wide current tracer (None
    restores the no-op default). Returns the PREVIOUS tracer so callers
    can restore it (the train loop does, keeping concurrent tests from
    leaking tracers into each other)."""
    global _current
    with _current_lock:
        prev = _current
        _current = tracer if tracer is not None else _null
    return prev


def current_tracer() -> SpanTracer:
    return _current


@contextmanager
def trace_span(name: str, **args: Any):
    """``with trace_span("outer_sync"):`` — record on the current
    tracer. The indirection is resolved at ENTRY so an install/restore
    race mid-span still closes the span on the tracer that opened it."""
    with _current.span(name, **args) as t:
        yield t


def trace_shard_path(path: str, process_index: int) -> str:
    """Where process ``k`` of a pod writes its trace shard:
    ``trace.json`` -> ``trace.rank1.json`` etc. Rank 0 keeps the
    requested path unchanged, so single-process behaviour (and every
    existing consumer of ``--trace-out``) is untouched."""
    if process_index == 0:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.rank{process_index}{ext or '.json'}"


def merge_chrome_traces(docs: list[dict[str, Any]]) -> dict[str, Any]:
    """Fold per-process trace shards into ONE Chrome trace: ``pid`` =
    process index, timestamps re-anchored onto a common wall clock, and
    process/thread-name metadata rewritten per pid — so the 2-process
    multihost run renders as a single Perfetto timeline where both
    hosts' ``sync`` spans line up (outer-step skew, finally visible).

    Alignment uses each shard's ``wall_start_unix`` anchor (recorded at
    tracer construction): shard timestamps are perf_counter-relative,
    so shifting each by ``(wall0_k - min(wall0)) * 1e6`` puts every
    shard on the earliest shard's clock. Shards without an anchor (a
    foreign trace) merge unshifted. Pid collisions (two shards both
    claiming rank 0) fall back to ordinal pids — the merge must never
    silently overlay two processes onto one lane."""
    if not docs:
        raise ValueError("no trace shards to merge")
    anchors = [
        (doc.get("otherData") or {}).get("wall_start_unix") for doc in docs
    ]
    known = [a for a in anchors if isinstance(a, (int, float))]
    base = min(known) if known else None
    merged: list[dict[str, Any]] = []
    used_pids: set[int] = set()
    for i, (doc, anchor) in enumerate(zip(docs, anchors)):
        other = doc.get("otherData") or {}
        pid = other.get("process_index")
        if not isinstance(pid, int) or pid in used_pids:
            pid = i
            while pid in used_pids:
                pid += 1
        used_pids.add(pid)
        shift_us = (
            (anchor - base) * 1e6
            if base is not None and isinstance(anchor, (int, float))
            else 0.0
        )
        saw_process_name = False
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "M":
                saw_process_name |= ev.get("name") == "process_name"
            elif "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            merged.append(ev)
        if not saw_process_name:
            merged.append({
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": f"nanodiloco rank{pid}"},
            })
    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "tracer": "nanodiloco_tpu.obs merge-trace",
            "merged_shards": len(docs),
            **({"wall_start_unix": base} if base is not None else {}),
        },
    }
