"""Lightweight host-side span tracer with Chrome trace-event export.

``jax.profiler`` answers "what is the DEVICE doing" at enormous capture
cost (one round, XLA-internal viewer); this tracer answers the
operator's daily question — "where does each ROUND's wall-clock go,
host-side, for the whole run" — at the cost of two ``perf_counter``
calls per span. Spans nest via a per-thread stack, export as Chrome
trace-event JSON (``chrome://tracing`` / Perfetto open it directly, no
jax tooling needed), and aggregate into per-phase totals
(``t_data``/``t_inner``/``t_sync``/...) that the train loop folds into
every sync's JSONL record, so a metrics stream alone reconstructs the
round budget.

Usage::

    with trace_span("outer_sync"):
        ...                      # nested trace_span calls nest in the UI

    tracer = current_tracer()
    totals = tracer.phase_totals()   # {"outer_sync": 0.173, ...}, resets
    tracer.export_chrome("trace.json")

The module-level current tracer makes instrumentation non-invasive:
library code calls ``trace_span`` unconditionally; when nothing
installed a real tracer the spans are recorded on a process-wide
default whose memory is bounded (``max_events``, oldest dropped).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, NamedTuple

from nanodiloco_tpu.obs import flightrec


class TraceContext(NamedTuple):
    """One hop's position in a causal trace.

    ``trace_id`` names the whole request tree (32 hex chars),
    ``span_id`` is THIS hop's own span (16 hex), ``parent_span_id`` the
    hop that caused it (None at the root), and ``sampled`` carries the
    head-based decision every downstream process must honour — the
    sampler runs once, at the edge, so a trace is either whole or
    absent, never half-collected.
    """

    trace_id: str
    span_id: str
    parent_span_id: str | None
    sampled: bool

    def child(self) -> "TraceContext":
        """A fresh span id parented under this one; trace id and the
        sampling decision ride along unchanged."""
        return TraceContext(self.trace_id, _new_span_id(),
                            self.span_id, self.sampled)

    def to_wire(self) -> str:
        """W3C-traceparent-style wire form
        (``00-<trace_id>-<span_id>-<flags>``): the receiver parents its
        spans under OUR span id. Flags: ``01`` sampled, ``00`` not."""
        return (f"00-{self.trace_id}-{self.span_id}-"
                f"{'01' if self.sampled else '00'}")

    @classmethod
    def from_wire(cls, wire: Any) -> "TraceContext | None":
        """Parse an incoming ``trace_context`` string; None on anything
        malformed (an old client or a garbage header must degrade to
        untraced, never to a 4xx)."""
        if not isinstance(wire, str):
            return None
        parts = wire.strip().split("-")
        if len(parts) != 4:
            return None
        _ver, tid, sid, flags = parts
        if len(tid) != 32 or len(sid) != 16:
            return None
        try:
            int(tid, 16), int(sid, 16)
        except ValueError:
            return None
        return cls(tid.lower(), sid.lower(), None, flags == "01")


def _new_span_id() -> str:
    return os.urandom(8).hex()


def _new_trace_id() -> str:
    return os.urandom(16).hex()


class SpanTracer:
    """Records nested host-side spans; thread-safe, clock-injectable.

    ``clock`` must be a monotonic seconds source (tests inject a fake).
    ``max_events`` bounds memory on long runs: a 10k-round run with ~8
    spans/round is ~80k events ≈ a few MB; beyond the cap the OLDEST
    events are dropped (the exported trace keeps the most recent
    window, which is the one an operator debugging a live run wants).
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        max_events: int = 500_000,
        process_index: int = 0,
        process_name: str | None = None,
        sample_rate: float = 1.0,
        reservoir_per_window: int = 2,
        reservoir_window_s: float = 60.0,
    ) -> None:
        self._clock = clock
        self._max_events = max_events
        # head-based sampling: the edge process (the one that mints the
        # trace id) decides once per trace; everyone downstream honours
        # the wire flag. The decision is a pure function of the trace id
        # so concurrent edge processes agree without coordination, plus
        # a bounded always-on reservoir (reservoir_per_window traces per
        # reservoir_window_s of this tracer's clock) so a production
        # rate of 0.01 still yields a steady trickle of whole traces.
        self.sample_rate = float(sample_rate)
        self._reservoir_per_window = int(reservoir_per_window)
        self._reservoir_window_s = float(reservoir_window_s)
        self._reservoir_left = self._reservoir_per_window
        self._reservoir_window_t0: float | None = None
        # which process of a multi-host pod this tracer records; carried
        # in the export's metadata so merge_chrome_traces can assign
        # stable pids (the train loop passes jax.process_index() — this
        # module itself stays jax-free)
        self.process_index = int(process_index)
        # display name for the Chrome process lane; default keeps the
        # training "nanodiloco rank{k}" convention. A serve-side tracer
        # names itself distinctly so a merged train+serve timeline shows
        # two labeled lanes instead of two anonymous rank0s.
        self.process_name = process_name or f"nanodiloco rank{self.process_index}"
        self._lock = threading.Lock()
        self._events: list[dict[str, Any]] = []
        self._dropped = 0
        # tid -> human thread name, recorded at span time so the export
        # can emit Chrome thread_name metadata (Perfetto then shows
        # main/prefetch/watchdog lanes instead of raw get_ident() ints)
        self._thread_names: dict[int, str] = {}
        self._local = threading.local()
        # wall-clock anchor: trace timestamps are perf_counter-relative;
        # recording the pairing at construction lets the export carry an
        # absolute start time in metadata
        self._t0 = self._clock()
        self._wall0 = time.time()
        # per-phase accumulation window (phase_totals resets it)
        self._totals: dict[str, float] = {}
        self._totals_depth0_t0: float | None = None

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    # -- causal context -------------------------------------------------

    def head_sample(self, trace_id: str) -> bool:
        """The once-per-trace sampling decision. Deterministic in the
        trace id (every edge process agrees), topped up by the bounded
        reservoir so some traces always survive a near-zero rate."""
        if self.sample_rate >= 1.0:
            return True
        if (self.sample_rate > 0.0
                and int(trace_id[:13] or "0", 16) / float(16 ** 13)
                < self.sample_rate):
            return True
        # reservoir: refill on window roll, measured on the tracer's own
        # clock (tests inject a fake; production gets perf_counter)
        now = self._clock()
        with self._lock:
            if (self._reservoir_window_t0 is None
                    or now - self._reservoir_window_t0
                    >= self._reservoir_window_s):
                self._reservoir_window_t0 = now
                self._reservoir_left = self._reservoir_per_window
            if self._reservoir_left > 0:
                self._reservoir_left -= 1
                return True
        return False

    def new_trace(self) -> TraceContext:
        """Mint a root context at the edge (the fleet router, or any
        process a request enters first)."""
        tid = _new_trace_id()
        return TraceContext(tid, _new_span_id(), None,
                            self.head_sample(tid))

    def accept(self, wire: Any) -> TraceContext:
        """Adopt an incoming wire context, or mint a fresh trace when
        there is none: the caller always gets a usable context, and a
        propagated sampling decision always wins over the local one."""
        ctx = TraceContext.from_wire(wire)
        if ctx is not None:
            return ctx
        return self.new_trace()

    @contextmanager
    def activate(self, ctx: TraceContext | None):
        """Bind ``ctx`` as this thread's remote parent: ``span()`` calls
        inside the block parent under it (depth-0 spans become children
        of the accepted context's span id). Nesting restores the outer
        binding on exit."""
        prev = getattr(self._local, "ctx", None)
        self._local.ctx = ctx
        try:
            yield self
        finally:
            self._local.ctx = prev

    def active_context(self) -> TraceContext | None:
        return getattr(self._local, "ctx", None)

    @contextmanager
    def span(self, name: str, **args: Any):
        """Record one span around the enclosed block. Exceptions
        propagate; the span still closes (the trace must show the round
        that crashed, not lose it). Under an activated sampled context
        the span gains causal ids: parent = the enclosing span on this
        thread's stack, else the accepted remote context."""
        stack = self._stack()
        depth = len(stack)
        ctx: TraceContext | None = getattr(self._local, "ctx", None)
        span_ctx: TraceContext | None = None
        if ctx is not None and ctx.sampled:
            parent = (stack[-1][1] or ctx) if stack else ctx
            span_ctx = parent.child()
        t0 = self._clock()
        stack.append((name, span_ctx if span_ctx is not None else ctx))
        try:
            yield self
        finally:
            stack.pop()
            t1 = self._clock()
            tid = threading.get_ident()
            ev = {
                "name": name,
                "t0": t0,
                "dur": t1 - t0,
                "depth": depth,
                "tid": tid,
            }
            if span_ctx is not None:
                args = dict(args)
                args["trace_id"] = span_ctx.trace_id
                args["span_id"] = span_ctx.span_id
                if span_ctx.parent_span_id:
                    args["parent_span_id"] = span_ctx.parent_span_id
            if args:
                ev["args"] = args
            with self._lock:
                if tid not in self._thread_names:
                    self._thread_names[tid] = threading.current_thread().name
                self._events.append(ev)
                if len(self._events) > self._max_events:
                    drop = len(self._events) - self._max_events
                    del self._events[:drop]
                    self._dropped += drop
                if depth == 0:
                    self._totals[name] = self._totals.get(name, 0.0) + (t1 - t0)
            if depth == 0:
                # black-box feed (obs/flightrec): the crash dump's last-N
                # timeline should show which phases ran up to the fatal
                # moment. One is-None check when no recorder is installed.
                flightrec.record_event("span", name=name, s=round(t1 - t0, 6))

    def record_span(
        self,
        name: str,
        t0: float,
        t1: float,
        ctx: TraceContext | None = None,
        **args: Any,
    ) -> None:
        """Record an ALREADY-TIMED span: ``t0``/``t1`` are values of
        THIS tracer's own clock, captured by the caller (the serve
        scheduler times request phases — queued/prefill/decode — with
        its injectable clock and reports them here after the fact; a
        context manager cannot wrap a wait that started on another
        thread). The caller must construct the tracer with the SAME
        clock it timestamps with, or the lanes won't line up. Recorded
        at depth 0, so serve phases aggregate into ``phase_totals``
        like the train loop's spans do.

        ``ctx`` names THIS span's place in a causal trace — the caller
        mints it (``parent_ctx.child()``) when it forwards work, then
        reports the span under the same ids after the fact. Unsampled
        or absent contexts add nothing to the event."""
        if self._max_events <= 0:
            return
        tid = threading.get_ident()
        ev = {
            "name": name,
            "t0": float(t0),
            "dur": max(0.0, float(t1) - float(t0)),
            "depth": 0,
            "tid": tid,
        }
        if ctx is not None and ctx.sampled:
            args = dict(args)
            args["trace_id"] = ctx.trace_id
            args["span_id"] = ctx.span_id
            if ctx.parent_span_id:
                args["parent_span_id"] = ctx.parent_span_id
        if args:
            ev["args"] = args
        with self._lock:
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            self._events.append(ev)
            if len(self._events) > self._max_events:
                drop = len(self._events) - self._max_events
                del self._events[:drop]
                self._dropped += drop
            self._totals[name] = self._totals.get(name, 0.0) + ev["dur"]

    def phase_totals(self, reset: bool = True) -> dict[str, float]:
        """Seconds per DEPTH-0 span name since the last reset — the
        per-round phase budget. Only top-level spans count, so nested
        detail spans never double-bill their parent phase."""
        with self._lock:
            out = dict(self._totals)
            if reset:
                self._totals = {}
        return out

    @property
    def events(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def to_chrome(self) -> dict[str, Any]:
        """Chrome trace-event JSON object (the ``{"traceEvents": [...]}``
        form). Complete ("X") events; nesting is implied by containment
        on the same tid, which Perfetto renders as a flame graph.
        Metadata ("M") events name the process (``rank{k}``) and each
        thread, so the timeline shows ``main``/``prefetch`` lanes, not
        raw thread-id integers."""
        pid = os.getpid()
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
            thread_names = dict(self._thread_names)
        tev: list[dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": self.process_name},
            }
        ]
        for tid, tname in sorted(thread_names.items()):
            tev.append({
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": tname},
            })
        tev += [
            {
                "name": e["name"],
                "ph": "X",
                "ts": (e["t0"] - self._t0) * 1e6,   # microseconds
                "dur": e["dur"] * 1e6,
                "pid": pid,
                "tid": e["tid"],
                **({"args": e["args"]} if "args" in e else {}),
            }
            for e in events
        ]
        return {
            "traceEvents": tev,
            "displayTimeUnit": "ms",
            "otherData": {
                "tracer": "nanodiloco_tpu.obs",
                "wall_start_unix": self._wall0,
                "process_index": self.process_index,
                **({"dropped_events": dropped} if dropped else {}),
            },
        }

    def export_chrome(self, path: str) -> str:
        """Write the Chrome trace JSON to ``path`` (atomic: tmp+rename,
        so a crash mid-write never leaves a torn file where an operator
        expects a trace). Returns the path."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome(), f)
        os.replace(tmp, path)
        return path


class _NullTracer(SpanTracer):
    """Default when nothing installed a tracer: records nothing — zero
    overhead beyond the context-manager call, and library code never
    needs an ``if tracing:`` guard."""

    def __init__(self) -> None:
        super().__init__(max_events=0)

    @contextmanager
    def span(self, name: str, **args: Any):
        yield self

    def phase_totals(self, reset: bool = True) -> dict[str, float]:
        return {}


_null = _NullTracer()
_current: SpanTracer = _null
_current_lock = threading.Lock()


def set_tracer(tracer: SpanTracer | None) -> SpanTracer:
    """Install ``tracer`` as the process-wide current tracer (None
    restores the no-op default). Returns the PREVIOUS tracer so callers
    can restore it (the train loop does, keeping concurrent tests from
    leaking tracers into each other)."""
    global _current
    with _current_lock:
        prev = _current
        _current = tracer if tracer is not None else _null
    return prev


def current_tracer() -> SpanTracer:
    return _current


@contextmanager
def trace_span(name: str, **args: Any):
    """``with trace_span("outer_sync"):`` — record on the current
    tracer. The indirection is resolved at ENTRY so an install/restore
    race mid-span still closes the span on the tracer that opened it."""
    with _current.span(name, **args) as t:
        yield t


def trace_shard_path(path: str, process_index: int) -> str:
    """Where process ``k`` of a pod writes its trace shard:
    ``trace.json`` -> ``trace.rank1.json`` etc. Rank 0 keeps the
    requested path unchanged, so single-process behaviour (and every
    existing consumer of ``--trace-out``) is untouched."""
    if process_index == 0:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.rank{process_index}{ext or '.json'}"


def merge_chrome_traces(docs: list[dict[str, Any]]) -> dict[str, Any]:
    """Fold per-process trace shards into ONE Chrome trace: ``pid`` =
    process index, timestamps re-anchored onto a common wall clock, and
    process/thread-name metadata rewritten per pid — so the 2-process
    multihost run renders as a single Perfetto timeline where both
    hosts' ``sync`` spans line up (outer-step skew, finally visible).

    Alignment uses each shard's ``wall_start_unix`` anchor (recorded at
    tracer construction): shard timestamps are perf_counter-relative,
    so shifting each by ``(wall0_k - min(wall0)) * 1e6`` puts every
    shard on the earliest shard's clock. Shards without an anchor (a
    foreign trace) merge unshifted. Pid collisions (two shards both
    claiming rank 0) fall back to ordinal pids — the merge must never
    silently overlay two processes onto one lane."""
    if not docs:
        raise ValueError("no trace shards to merge")
    anchors = [
        (doc.get("otherData") or {}).get("wall_start_unix") for doc in docs
    ]
    known = [a for a in anchors if isinstance(a, (int, float))]
    base = min(known) if known else None
    merged: list[dict[str, Any]] = []
    used_pids: set[int] = set()
    for i, (doc, anchor) in enumerate(zip(docs, anchors)):
        other = doc.get("otherData") or {}
        pid = other.get("process_index")
        if not isinstance(pid, int) or pid in used_pids:
            pid = i
            while pid in used_pids:
                pid += 1
        used_pids.add(pid)
        shift_us = (
            (anchor - base) * 1e6
            if base is not None and isinstance(anchor, (int, float))
            else 0.0
        )
        saw_process_name = False
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "M":
                saw_process_name |= ev.get("name") == "process_name"
            elif "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            merged.append(ev)
        if not saw_process_name:
            merged.append({
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": f"nanodiloco rank{pid}"},
            })
    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "tracer": "nanodiloco_tpu.obs merge-trace",
            "merged_shards": len(docs),
            **({"wall_start_unix": base} if base is not None else {}),
        },
    }


# -- causal assembly: shards -> one tree -> where the latency went ------

_EPS = 1e-9


def stitch_trace(docs: list[dict[str, Any]], needle: str) -> dict[str, Any]:
    """Assemble ONE request's causal tree from per-process trace shards.

    ``needle`` is a trace id or a request id. Shards are re-anchored
    onto a common wall clock exactly like ``merge_chrome_traces``; the
    needle is first resolved BOTH ways (a request id pulls in every
    trace id its spans carry and vice versa), then every matching span
    becomes a node and nodes link by ``parent_span_id``. Spans from
    uninstrumented/old shards carry no ids but still join by request id
    — they surface as extra roots under a synthetic ``trace`` node, so
    a fleet mid-rollout still yields one tree instead of an error.
    Times are seconds, rebased so the earliest span starts at 0."""
    merged = merge_chrome_traces(docs)
    pname: dict[Any, str] = {}
    xevents: list[dict[str, Any]] = []
    for ev in merged["traceEvents"]:
        if ev.get("ph") == "M":
            if ev.get("name") == "process_name":
                pname[ev.get("pid")] = (ev.get("args") or {}).get("name")
        elif ev.get("ph") == "X":
            xevents.append(ev)
    trace_ids, request_ids = {needle}, {needle}
    for ev in xevents:
        a = ev.get("args") or {}
        if a.get("trace_id") == needle and a.get("request_id"):
            request_ids.add(a["request_id"])
        if a.get("request_id") == needle and a.get("trace_id"):
            trace_ids.add(a["trace_id"])
    by_id: dict[str, dict] = {}
    picked: list[dict] = []
    for ev in xevents:
        a = ev.get("args") or {}
        if not (a.get("trace_id") in trace_ids
                or a.get("request_id") in request_ids):
            continue
        node = {
            "name": ev.get("name"),
            "process": pname.get(ev.get("pid")) or f"pid{ev.get('pid')}",
            "start_s": float(ev.get("ts") or 0.0) / 1e6,
            "dur_s": max(0.0, float(ev.get("dur") or 0.0) / 1e6),
            "span_id": a.get("span_id"),
            "parent_span_id": a.get("parent_span_id"),
            "trace_id": a.get("trace_id"),
            "request_id": a.get("request_id"),
            "args": {k: v for k, v in a.items()
                     if k not in ("trace_id", "span_id", "parent_span_id")},
            "children": [],
        }
        node["end_s"] = node["start_s"] + node["dur_s"]
        picked.append(node)
        if node["span_id"]:
            by_id.setdefault(node["span_id"], node)
    if not picked:
        raise ValueError(f"no spans match {needle!r} in the given shards")
    t_min = min(n["start_s"] for n in picked)
    for n in picked:
        n["start_s"] -= t_min
        n["end_s"] -= t_min
    roots: list[dict] = []
    for n in sorted(picked, key=lambda n: (n["start_s"], -n["dur_s"])):
        parent = by_id.get(n["parent_span_id"]) if n["parent_span_id"] else None
        if parent is not None and parent is not n:
            parent["children"].append(n)
        else:
            roots.append(n)
    tid = next((n["trace_id"] for n in picked if n["trace_id"]), None)
    if len(roots) == 1:
        root = roots[0]
    else:
        # >1 root: shards joined only by request id (old emitters), or a
        # torn trace — a synthetic node makes the slack between them an
        # honest residual instead of an invisible drop
        root = {
            "name": "trace", "process": "(stitched)",
            "span_id": None, "parent_span_id": None,
            "trace_id": tid, "request_id": None, "args": {},
            "start_s": min(n["start_s"] for n in roots),
            "end_s": max(n["end_s"] for n in roots),
            "children": roots,
        }
        root["dur_s"] = root["end_s"] - root["start_s"]
    return {
        "root": root,
        "spans": picked,
        "trace_id": tid,
        "request_ids": sorted(r for r in {n["request_id"] for n in picked}
                              if r),
        "causal_spans": sum(1 for n in picked if n["span_id"]),
        "request_id_joined": sum(1 for n in picked if not n["span_id"]),
        "shards": len(docs),
    }


def critical_path(root: dict[str, Any]) -> list[dict[str, Any]]:
    """The chain of segments that determined the root span's duration:
    walk backwards from each span's end to the latest-ending child that
    could have gated it, recurse, and book every uncovered stretch to
    the span that owned the clock at that moment. Segment kinds:
    ``span`` (a leaf's own work), ``self`` (a parent's own leading
    work), ``residual`` (time inside a parent covered by NO child —
    network, queue slack between hops, cross-shard stitch skew —
    reported as its own segment, never dropped). Segments partition
    ``[root.start, root.end]`` exactly, so they sum to the root
    duration by construction."""
    segs: list[dict[str, Any]] = []

    def seg(node: dict, t0: float, t1: float, kind: str) -> None:
        if t1 - t0 > _EPS:
            segs.append({
                "span": node["name"], "process": node["process"],
                "t0_s": t0, "t1_s": t1, "seconds": t1 - t0, "kind": kind,
                **({"outcome": node["args"]["outcome"]}
                   if node.get("args", {}).get("outcome") else {}),
            })

    def walk(node: dict, t_hi: float) -> None:
        t = min(node["end_s"], t_hi)
        remaining = list(node["children"])
        while True:
            best, best_e = None, 0.0
            for c in remaining:
                ce = min(c["end_s"], t)
                if ce - c["start_s"] <= _EPS:
                    continue
                if best is None or ce > best_e:
                    best, best_e = c, ce
            if best is None:
                break
            remaining.remove(best)
            seg(node, best_e, t, "residual")
            walk(best, best_e)
            t = max(best["start_s"], node["start_s"])
        seg(node, node["start_s"], t,
            "span" if not node["children"] else "self")

    walk(root, root["end_s"])
    segs.sort(key=lambda s: s["t0_s"])
    return segs


def render_waterfall(stitched: dict[str, Any], width: int = 56) -> str:
    """ASCII waterfall of a stitched trace: one row per span, bar
    position/length proportional to when it ran inside the root span."""
    root = stitched["root"]
    total = max(root["end_s"] - root["start_s"], _EPS)
    lines: list[str] = []

    def row(node: dict, depth: int) -> None:
        off = int((node["start_s"] - root["start_s"]) / total * width)
        w = max(1, round((node["end_s"] - node["start_s"]) / total * width))
        off = min(off, width - 1)
        bar = " " * off + "#" * min(w, width - off)
        label = ("  " * depth + node["name"])[:26]
        outcome = (node.get("args") or {}).get("outcome")
        tail = f"  [{outcome}]" if outcome else ""
        dur_s = node["end_s"] - node["start_s"]
        lines.append(
            f"{label:<26s} |{bar:<{width}s}| "
            f"{dur_s * 1e3:9.3f} ms  {node['process']}{tail}"
        )
        for c in sorted(node["children"], key=lambda c: c["start_s"]):
            row(c, depth + 1)

    row(root, 0)
    return "\n".join(lines)
