"""Tokenizers.

The reference hard-depends on fetching ``huggyllama/llama-7b`` from the
HF hub (ref nanodiloco/training_utils/utils.py:57-60) — impossible in an
offline TPU pod. Here the HF tokenizer is used when available (cached or
local path) with a deterministic, dependency-free byte-level fallback, so
the training stack is runnable anywhere.
"""

from __future__ import annotations

from typing import Protocol


class Tokenizer(Protocol):
    vocab_size: int
    pad_id: int
    eos_id: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """Byte-level tokenizer: ids 0..255 are raw bytes; 256=pad, 257=bos,
    258=eos. Vocab padded to 384 (divisible by 128) so the lm_head matmul
    tiles cleanly onto the MXU."""

    vocab_size = 384
    pad_id = 256
    bos_id = 257
    eos_id = 258

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", errors="replace")


class HFTokenizer:
    """Wrapper over a transformers tokenizer, matching the reference's
    pad-token choice (``</s>``, ref utils.py:59)."""

    def __init__(self, name_or_path: str = "huggyllama/llama-7b"):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name_or_path)
        if self._tok.pad_token is None:
            self._tok.pad_token = self._tok.eos_token or "</s>"
        self.vocab_size = len(self._tok)
        self.pad_id = self._tok.pad_token_id
        self.eos_id = self._tok.eos_token_id

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=add_bos)
        if add_eos and self.eos_id is not None:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids) -> str:
        return self._tok.decode(list(ids))


def get_tokenizer(name_or_path: str | None = None) -> Tokenizer:
    """HF tokenizer when reachable (local cache/path), else ByteTokenizer.
    Mirrors the reference's get_tokenizer (ref utils.py:57-60) but never
    requires network access. A failed explicit request falls back WITH a
    warning — silent vocab switches corrupt runs invisibly."""
    if name_or_path:
        try:
            return HFTokenizer(name_or_path)
        except Exception as e:
            import warnings

            warnings.warn(
                f"could not load tokenizer {name_or_path!r} ({type(e).__name__}: {e}); "
                "falling back to the 384-token byte-level tokenizer",
                stacklevel=2,
            )
    return ByteTokenizer()
