"""ctypes bindings for the native tokenshard reader (csrc/tokenshard.cpp).

The shared library is built on first use with g++ (cached beside the
source); every call degrades gracefully to a pure-numpy implementation
when no compiler is available, so the framework never hard-depends on
the native layer — it is a throughput upgrade, not a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SRC = os.path.join(_CSRC, "tokenshard.cpp")
_LIB_PATH = os.path.join(_CSRC, "libtokenshard.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False

_MAGIC = b"TSHRD\x01\x00\x00"
_HEADER = 24


def _build_and_load() -> ctypes.CDLL | None:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_LIB_PATH) or (
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
            ):
                subprocess.run(
                    ["g++", "-O3", "-march=native", "-std=c++17", "-shared",
                     "-fPIC", "-pthread", "-o", _LIB_PATH, _SRC],
                    check=True, capture_output=True,
                )
            lib = ctypes.CDLL(_LIB_PATH)
            lib.ts_write.restype = ctypes.c_int
            lib.ts_write.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                     ctypes.c_uint64, ctypes.c_uint64]
            lib.ts_open.restype = ctypes.c_void_p
            lib.ts_open.argtypes = [ctypes.c_char_p]
            lib.ts_n_seqs.restype = ctypes.c_uint64
            lib.ts_n_seqs.argtypes = [ctypes.c_void_p]
            lib.ts_seq_len.restype = ctypes.c_uint64
            lib.ts_seq_len.argtypes = [ctypes.c_void_p]
            lib.ts_close.argtypes = [ctypes.c_void_p]
            lib.ts_gather.restype = ctypes.c_int
            lib.ts_gather.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_uint64, ctypes.c_void_p, ctypes.c_int]
            lib.ts_shuffled_indices.argtypes = [
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_void_p,
            ]
            _lib = lib
        except Exception:
            _lib_failed = True
            _lib = None
        return _lib


def native_available() -> bool:
    return _build_and_load() is not None


def write_shard(path: str, data: np.ndarray) -> None:
    """Write [N, S] int32 tokens to a tokenshard file."""
    data = np.ascontiguousarray(data, dtype=np.int32)
    if data.ndim != 2:
        raise ValueError(f"data must be [N, S]; got {data.shape}")
    lib = _build_and_load()
    if lib is not None:
        rc = lib.ts_write(path.encode(), data.ctypes.data, data.shape[0], data.shape[1])
        if rc != 0:
            raise OSError(f"ts_write failed with code {rc} for {path}")
        return
    with open(path, "wb") as f:  # numpy fallback, same format
        f.write(_MAGIC)
        f.write(np.asarray(data.shape, dtype=np.uint64).tobytes())
        f.write(data.tobytes())


class ShardWriter:
    """Append-mode tokenshard writer with bounded memory: open, append
    [K, S] row blocks as a streaming tokenizer produces them, and
    ``close()`` patches the final row count into the header — so a
    corpus larger than host RAM can be materialized without ever holding
    it (VERDICT r3 missing #1). The resulting file is byte-identical to
    ``write_shard`` of the concatenated rows (same header layout,
    csrc/tokenshard.cpp:15-19; appends are plain I/O, so no native-layer
    dependence).

    Writes go to ``path + ".tmp"`` and an atomic ``os.replace`` installs
    the file only on a successful close — a failed or aborted run can
    never truncate a previously good shard at ``path`` or leave a
    valid-looking partial one behind (a crashed process may leave the
    ``.tmp`` file; it is overwritten by the next attempt). As a context
    manager, an exception inside the block discards the temp file."""

    def __init__(self, path: str, seq_len: int):
        if seq_len < 1:
            raise ValueError(f"seq_len must be >= 1; got {seq_len}")
        self.path = path
        self.seq_len = int(seq_len)
        self.n_seqs = 0
        self._tmp = path + ".tmp"
        self._f = open(self._tmp, "wb")
        self._f.write(_MAGIC)
        self._f.write(np.asarray([0, self.seq_len], dtype=np.uint64).tobytes())

    def append(self, rows: np.ndarray) -> None:
        rows = np.ascontiguousarray(rows, dtype=np.int32)
        if rows.ndim != 2 or rows.shape[1] != self.seq_len:
            raise ValueError(
                f"rows must be [K, {self.seq_len}]; got {rows.shape}"
            )
        self._f.write(rows.tobytes())
        self.n_seqs += int(rows.shape[0])

    def close(self, commit: bool = True) -> None:
        if self._f.closed:
            return
        self._f.flush()
        self._f.seek(8)
        self._f.write(np.asarray([self.n_seqs], dtype=np.uint64).tobytes())
        self._f.close()
        if commit:
            os.replace(self._tmp, self.path)
        else:
            try:
                os.unlink(self._tmp)
            except OSError:
                pass

    def __enter__(self) -> "ShardWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        self.close(commit=exc_type is None)


class TokenShard:
    """Reader for one shard file: mmap'd rows + deterministic shuffling.

    ``batch(indices)`` gathers rows into a fresh [len(indices), S] array
    (threaded memcpy natively); ``shuffled_indices(seed, epoch, worker)``
    is the C++ Fisher-Yates (or a bit-identical numpy re-implementation
    in fallback mode — both derive from splitmix64, so mixing native and
    fallback hosts still yields identical batch order).
    """

    def __init__(self, path: str):
        self.path = path
        self._lib = _build_and_load()
        self._handle = None
        if self._lib is not None:
            self._handle = self._lib.ts_open(path.encode())
            if not self._handle:
                raise OSError(f"cannot open tokenshard {path}")
            self.n_seqs = int(self._lib.ts_n_seqs(self._handle))
            self.seq_len = int(self._lib.ts_seq_len(self._handle))
        else:
            with open(path, "rb") as f:
                header = f.read(_HEADER)
            if header[:8] != _MAGIC:
                raise OSError(f"bad magic in {path}")
            n, s = np.frombuffer(header[8:], dtype=np.uint64)
            self.n_seqs, self.seq_len = int(n), int(s)
            self._mm = np.memmap(path, dtype=np.int32, mode="r", offset=_HEADER,
                                 shape=(self.n_seqs, self.seq_len))

    def batch(self, indices: np.ndarray, n_threads: int = 0) -> np.ndarray:
        indices = np.ascontiguousarray(indices, dtype=np.uint64)
        if self._handle is not None:
            out = np.empty((len(indices), self.seq_len), dtype=np.int32)
            rc = self._lib.ts_gather(
                self._handle, indices.ctypes.data, len(indices),
                out.ctypes.data, n_threads,
            )
            if rc != 0:
                raise IndexError(f"tokenshard index out of range (rc={rc})")
            return out
        if (indices >= self.n_seqs).any():
            raise IndexError("tokenshard index out of range")
        return np.asarray(self._mm[indices.astype(np.int64)])

    def shuffled_indices(self, seed: int, epoch: int, worker: int) -> np.ndarray:
        out = np.empty(self.n_seqs, dtype=np.uint64)
        if self._handle is not None:
            self._lib.ts_shuffled_indices(self.n_seqs, seed, epoch, worker,
                                          out.ctypes.data)
            return out
        return _py_shuffled_indices(self.n_seqs, seed, epoch, worker)

    def close(self) -> None:
        if self._handle is not None:
            self._lib.ts_close(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


def _splitmix64(state: np.uint64) -> tuple[np.uint64, np.uint64]:
    with np.errstate(over="ignore"):
        state = np.uint64(state + np.uint64(0x9E3779B97F4A7C15))
        z = state
        z = np.uint64((z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9))
        z = np.uint64((z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB))
        return state, np.uint64(z ^ (z >> np.uint64(31)))


def _py_shuffled_indices(n: int, seed: int, epoch: int, worker: int) -> np.ndarray:
    """Bit-identical to ts_shuffled_indices in csrc/tokenshard.cpp."""
    out = np.arange(n, dtype=np.uint64)
    with np.errstate(over="ignore"):
        s = np.uint64(
            np.uint64(seed) * np.uint64(0x9E3779B97F4A7C15)
            + np.uint64(epoch) * np.uint64(0xBF58476D1CE4E5B9)
            + np.uint64(worker) * np.uint64(0x94D049BB133111EB)
            + np.uint64(1)
        )
    for i in range(n, 1, -1):
        s, r = _splitmix64(s)
        j = int(r % np.uint64(i))
        out[i - 1], out[j] = out[j], out[i - 1]
    return out
