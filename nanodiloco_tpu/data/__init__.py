from nanodiloco_tpu.data.pipeline import (
    DilocoBatcher,
    load_hf_dataset_texts,
    pack_corpus,
    pad_corpus,
    synthetic_corpus,
)
from nanodiloco_tpu.data.tokenizer import ByteTokenizer, HFTokenizer, get_tokenizer

__all__ = [
    "DilocoBatcher",
    "pack_corpus",
    "pad_corpus",
    "synthetic_corpus",
    "load_hf_dataset_texts",
    "get_tokenizer",
    "ByteTokenizer",
    "HFTokenizer",
]
