from nanodiloco_tpu.data.pipeline import (
    DilocoBatcher,
    iter_hf_dataset_texts,
    load_hf_dataset_texts,
    pack_corpus,
    pack_corpus_to_shard,
    pad_corpus,
    synthetic_corpus,
)
from nanodiloco_tpu.data.tokenizer import ByteTokenizer, HFTokenizer, get_tokenizer

__all__ = [
    "DilocoBatcher",
    "pack_corpus",
    "pack_corpus_to_shard",
    "pad_corpus",
    "synthetic_corpus",
    "iter_hf_dataset_texts",
    "load_hf_dataset_texts",
    "get_tokenizer",
    "ByteTokenizer",
    "HFTokenizer",
]
