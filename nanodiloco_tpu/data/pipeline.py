"""Data pipeline: tokenize -> pack -> deterministic per-worker batches.

TPU-first redesign of the reference's pipeline (ref
nanodiloco/training_utils/utils.py:45-55 + nanodiloco/main.py:75-96):

- The reference tokenizes with truncation at 1024 and pads each batch to
  its longest example (dynamic shapes per batch, loss computed on pad,
  ref main.py:79-88). Here documents are PACKED into fixed-length
  sequences: static shapes for a single jit cache entry, zero pad waste,
  no masks on the hot path. A ``padded`` mode reproduces the reference's
  per-document layout (with correct pad masking) when needed.
- ``split_dataset_by_node`` (ref main.py:77) becomes a deterministic
  strided shard per DiLoCo worker; shuffle/drop_last (ref main.py:94-95)
  become a seeded per-epoch permutation — identical on every host, so
  multi-host data order needs no communication.
- Batches come out in the DiLoCo engine's native layout
  [num_workers, grad_accum, per_device_batch, seq_len].
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

from nanodiloco_tpu.data.tokenizer import Tokenizer


# ---------------------------------------------------------------------------
# Corpus sources
# ---------------------------------------------------------------------------

def synthetic_corpus(n_docs: int = 2000, seed: int = 0) -> list[str]:
    """Deterministic pseudo-English corpus for offline tests/benches.
    Structured (zipfian vocabulary, repeated phrases) so models can
    actually learn from it, unlike uniform noise."""
    rng = np.random.default_rng(seed)
    vocab = [
        "the", "of", "and", "to", "in", "a", "is", "that", "for", "it",
        "model", "data", "train", "step", "loss", "worker", "sync", "token",
        "mesh", "shard", "device", "batch", "grad", "outer", "inner", "ring",
    ]
    probs = 1.0 / np.arange(1, len(vocab) + 1)
    probs /= probs.sum()
    docs = []
    for _ in range(n_docs):
        n_words = int(rng.integers(20, 200))
        words = rng.choice(vocab, size=n_words, p=probs)
        docs.append(" ".join(words) + ".")
    return docs


def iter_hf_dataset_texts(
    path: str, split: str = "train", column: str = "text"
) -> Iterator[str]:
    """Stream texts from a ``datasets.save_to_disk`` directory — the
    reference's on-disk c4-tiny layout (ref
    scripts/setup_data_volume.py:27-56, utils.py:45-55). Rows come off
    the Arrow mmap one at a time, so a corpus larger than host RAM can
    be materialized (VERDICT r3 missing #1); the reference's
    ``datasets.map`` pipeline streams through Arrow the same way."""
    from datasets import load_from_disk

    ds = load_from_disk(path)
    if hasattr(ds, "keys") and split in getattr(ds, "keys", lambda: [])():
        ds = ds[split]
    # decode only the needed column per row — `for row in ds` would build
    # a dict of EVERY column per record (c4 carries url/timestamp too)
    if hasattr(ds, "select_columns"):
        ds = ds.select_columns([column])
    for row in ds:
        yield row[column]


def load_hf_dataset_texts(path: str, split: str = "train", column: str = "text") -> list[str]:
    """Materialized convenience wrapper over ``iter_hf_dataset_texts``
    for corpora known to fit in RAM; the scaling path is the iterator +
    ``pack_corpus_to_shard``."""
    return list(iter_hf_dataset_texts(path, split, column))


# ---------------------------------------------------------------------------
# Tokenize + pack
# ---------------------------------------------------------------------------

def pack_corpus(
    texts: list[str], tokenizer: Tokenizer, seq_length: int = 1024
) -> np.ndarray:
    """Tokenize all docs (eos-separated) and pack the token stream into
    [N, seq_length] int32 rows. The trailing partial block is dropped."""
    stream: list[int] = []
    for t in texts:
        stream.extend(tokenizer.encode(t, add_eos=True))
    n = len(stream) // seq_length
    if n == 0:
        raise ValueError(
            f"corpus too small: {len(stream)} tokens < seq_length {seq_length}"
        )
    arr = np.asarray(stream[: n * seq_length], dtype=np.int32)
    return arr.reshape(n, seq_length)


def pack_corpus_to_shard(
    texts,
    tokenizer: Tokenizer,
    seq_length: int,
    writer,
    flush_rows: int = 1024,
) -> int:
    """Streaming tokenize -> pack: the same packing as ``pack_corpus``
    (eos-separated token stream cut into [seq_length] rows, trailing
    partial dropped) but emitted to a ``tokenshard.ShardWriter`` in
    ``flush_rows``-row blocks, so peak host memory is
    O(flush_rows x seq_length + one document) no matter how large the
    corpus — the past-RAM materialization path (VERDICT r3 missing #1;
    the reference leaned on HF datasets' Arrow cache for the same,
    ref training_utils/utils.py:45-55). ``texts`` is any iterable of
    documents (use ``iter_hf_dataset_texts`` / a file-walking generator
    to keep the source streaming too). Returns rows written; the shard
    is bit-identical to ``write_shard(pack_corpus(texts, ...))``."""
    if flush_rows < 1:
        raise ValueError(f"flush_rows must be >= 1; got {flush_rows}")
    buf: list[int] = []
    rows = 0
    total_tokens = 0  # all tokens seen, not just the unflushed remainder
    limit = flush_rows * seq_length
    for t in texts:
        enc = tokenizer.encode(t, add_eos=True)
        total_tokens += len(enc)
        buf.extend(enc)
        if len(buf) >= limit:
            n = len(buf) // seq_length
            block = np.asarray(buf[: n * seq_length], dtype=np.int32)
            writer.append(block.reshape(n, seq_length))
            rows += n
            del buf[: n * seq_length]
    n = len(buf) // seq_length
    if n:
        block = np.asarray(buf[: n * seq_length], dtype=np.int32)
        writer.append(block.reshape(n, seq_length))
        rows += n
    if rows == 0:
        raise ValueError(
            f"corpus too small: {total_tokens} tokens < seq_length "
            f"{seq_length}"
        )
    return rows


def pad_corpus(
    texts: list[str], tokenizer: Tokenizer, seq_length: int = 1024
) -> tuple[np.ndarray, np.ndarray]:
    """Reference-style layout: one document per row, truncated at
    seq_length (ref utils.py:50), padded to a multiple of 8 columns
    (ref main.py:84). Returns (tokens [N, S'], mask [N, S']) with pad
    positions masked OUT of the loss (fixing ref main.py:87)."""
    encoded = [tokenizer.encode(t)[:seq_length] for t in texts]
    encoded = [e for e in encoded if len(e) >= 2]
    max_len = max(len(e) for e in encoded)
    max_len = ((max_len + 7) // 8) * 8
    tokens = np.full((len(encoded), max_len), tokenizer.pad_id, dtype=np.int32)
    mask = np.zeros((len(encoded), max_len), dtype=np.int32)
    for i, e in enumerate(encoded):
        tokens[i, : len(e)] = e
        mask[i, : len(e)] = 1
    return tokens, mask


# ---------------------------------------------------------------------------
# Deterministic per-worker batching
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DilocoBatcher:
    """Yields ([W, accum, B, S] tokens, same-shape mask) batches.

    Worker w reads the strided shard ``data[w::num_workers]`` (the
    deterministic analog of split_dataset_by_node, ref main.py:77), with
    a per-epoch seeded permutation per worker and drop_last semantics
    (ref main.py:94-95). Fully reproducible from ``seed`` alone; no state
    lives outside this object.
    """

    data: np.ndarray                 # [N, S] int32
    num_workers: int
    grad_accum: int
    per_device_batch: int
    seed: int = 1337
    mask: np.ndarray | None = None   # [N, S]; None -> all-ones

    def __post_init__(self) -> None:
        if self.data.ndim != 2:
            raise ValueError(f"data must be [N, S]; got {self.data.shape}")
        self._shards = [
            np.arange(w, len(self.data), self.num_workers)
            for w in range(self.num_workers)
        ]
        per_step = self.grad_accum * self.per_device_batch
        self.steps_per_epoch = min(len(s) for s in self._shards) // per_step
        if self.steps_per_epoch == 0:
            raise ValueError(
                f"shards of {min(len(s) for s in self._shards)} sequences cannot "
                f"fill one inner step of {per_step} ({self.grad_accum} microbatches "
                f"x {self.per_device_batch})"
            )

    def epoch(self, epoch: int, start_step: int = 0) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """One pass over every worker's shard, shuffled per (seed, epoch,
        worker), trailing remainder dropped. ``start_step`` skips forward
        without materializing the skipped batches (O(1) resume)."""
        W, A, B = self.num_workers, self.grad_accum, self.per_device_batch
        S = self.data.shape[1]
        per_step = A * B
        orders = [
            self._shards[w][
                np.random.default_rng((self.seed, epoch, w)).permutation(len(self._shards[w]))
            ]
            for w in range(W)
        ]
        for step in range(start_step, self.steps_per_epoch):
            tokens = np.empty((W, A, B, S), dtype=np.int32)
            mask = np.empty((W, A, B, S), dtype=np.int32)
            for w in range(W):
                idx = orders[w][step * per_step : (step + 1) * per_step]
                tokens[w] = self.data[idx].reshape(A, B, S)
                mask[w] = (
                    self.mask[idx].reshape(A, B, S)
                    if self.mask is not None
                    else np.ones((A, B, S), np.int32)
                )
            yield tokens, mask

    def iter_from(self, global_step: int) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """Endless stream positioned at ``global_step`` inner steps from
        the beginning — deterministic resume without replaying data."""
        epoch, offset = divmod(global_step, self.steps_per_epoch)
        while True:
            yield from self.epoch(epoch, start_step=offset)
            epoch, offset = epoch + 1, 0

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """Endless stream across epochs (the reference iterates its
        DataLoader once and simply stops at shard exhaustion,
        ref main.py:106; callers here bound the run by total_steps)."""
        return self.iter_from(0)


@dataclasses.dataclass
class ShardBatcher:
    """DilocoBatcher backed by the native tokenshard reader
    (csrc/tokenshard.cpp): mmap'd rows, threaded gather, and the
    in-library deterministic shuffle. Same [W, accum, B, S] output
    contract; batch ORDER differs from DilocoBatcher (different PRNG) but
    is itself fully deterministic from the seed on every host."""

    path: str
    num_workers: int
    grad_accum: int
    per_device_batch: int
    seed: int = 1337
    holdout_rows: int = 0            # trailing rows reserved for evaluation

    def __post_init__(self) -> None:
        from nanodiloco_tpu.data.tokenshard import TokenShard

        self._ts = TokenShard(self.path)
        self.seq_len = self._ts.seq_len
        self._n_train = self._ts.n_seqs - self.holdout_rows
        if self._n_train <= 0:
            raise ValueError(
                f"holdout_rows={self.holdout_rows} leaves no training rows "
                f"(shard has {self._ts.n_seqs})"
            )
        n_shard = min(
            len(range(w, self._n_train, self.num_workers))
            for w in range(self.num_workers)
        )
        per_step = self.grad_accum * self.per_device_batch
        self.steps_per_epoch = n_shard // per_step
        self._n_shard = n_shard
        if self.steps_per_epoch == 0:
            raise ValueError(
                f"shards of {n_shard} sequences cannot fill one inner step of "
                f"{per_step} ({self.grad_accum} x {self.per_device_batch})"
            )

    def epoch(self, epoch: int, start_step: int = 0) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        W, A, B, S = self.num_workers, self.grad_accum, self.per_device_batch, self.seq_len
        per_step = A * B
        from nanodiloco_tpu.data.tokenshard import _py_shuffled_indices
        orders = []
        for w in range(W):
            # permute the worker's strided shard positions, then map to
            # global row ids (w + W * local)
            if self._ts._handle is not None:
                local = np.empty(self._n_shard, dtype=np.uint64)
                self._ts._lib.ts_shuffled_indices(
                    self._n_shard, self.seed, epoch, w, local.ctypes.data
                )
            else:
                local = _py_shuffled_indices(self._n_shard, self.seed, epoch, w)
            orders.append(np.uint64(w) + np.uint64(W) * local)
        for step in range(start_step, self.steps_per_epoch):
            tokens = np.empty((W, A, B, S), dtype=np.int32)
            for w in range(W):
                idx = orders[w][step * per_step : (step + 1) * per_step]
                tokens[w] = self._ts.batch(idx).reshape(A, B, S)
            yield tokens, np.ones_like(tokens)

    def iter_from(self, global_step: int) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """O(1)-skip endless stream (see DilocoBatcher.iter_from)."""
        epoch, offset = divmod(global_step, self.steps_per_epoch)
        while True:
            yield from self.epoch(epoch, start_step=offset)
            epoch, offset = epoch + 1, 0

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        return self.iter_from(0)

    def holdout_data(self) -> np.ndarray:
        """The reserved trailing rows [holdout_rows, S] (never trained on)."""
        if not self.holdout_rows:
            return np.empty((0, self.seq_len), np.int32)
        idx = np.arange(self._n_train, self._ts.n_seqs, dtype=np.uint64)
        return self._ts.batch(idx)

    def close(self) -> None:
        self._ts.close()
