"""Host-side KV block allocator: the policy half of the paged cache.

The arena on device is ``[L, num_blocks, block_size, Hkv, hd]``; this
class owns WHICH physical blocks belong to WHOM. Blocks are fully
interchangeable (any block can hold any sequence's rows — the block
table, not adjacency, defines order), so a free list is
fragmentation-free by construction: an allocation succeeds iff enough
blocks are free, regardless of how past allocations interleaved.

Reference counting makes shared-prefix reuse copy-on-write for free:
a newly allocated block has refcount 1 (its slot); mapping it into
another slot's table or into the prefix cache's chunk registry bumps
the count; every holder ``deref``s on release, and the block returns
to the free list only at zero. Writers never touch a shared block —
the engine only writes at positions past its prefix-hit boundary, and
those always live in refcount-1 blocks — so "copy"-on-write never
actually copies: divergent suffixes were never shared to begin with.

``alloc`` is ALL-OR-NOTHING: it either returns the full set or raises
``BlocksExhausted`` having mutated nothing, so a failed admission can
never leak a partial allocation (the scheduler leaves the request
queued and retries when blocks free up). Single-threaded by design
(the engine tick thread); ``stats`` reads plain ints and is safe from
HTTP threads.
"""

from __future__ import annotations


class BlocksExhausted(RuntimeError):
    """Raised by ``alloc`` when the pool cannot currently supply the
    requested blocks — the retryable admission signal (distinct from a
    request that can NEVER fit, which is a ``ValueError`` at
    validation). The scheduler keeps the request queued."""


class BlockPool:
    """Free-list + refcount allocator over ``num_blocks`` interchangeable
    KV blocks of ``block_size`` token rows each."""

    def __init__(self, num_blocks: int, block_size: int) -> None:
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1; got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1; got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: ids are popped from the end, so recently freed
        # blocks are reused first (warm-ish HBM, and deterministic)
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._ref = [0] * self.num_blocks
        self.total_allocated = 0   # blocks ever handed out (counter)
        self.total_freed = 0       # blocks ever returned (counter)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    def alloc(self, n: int) -> list[int]:
        """``n`` fresh blocks at refcount 1, or ``BlocksExhausted`` with
        the pool untouched (all-or-nothing — no partial allocation to
        roll back)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            raise BlocksExhausted(
                f"need {n} KV blocks but only {len(self._free)}/"
                f"{self.num_blocks} are free"
            )
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._ref[b] = 1
        self.total_allocated += n
        return blocks

    def ref(self, blocks) -> None:
        """Add one reference to each live block (a second slot or the
        prefix cache mapping it). Refusing dead blocks loudly turns a
        table-bookkeeping bug into a test failure, not silent
        corruption."""
        for b in blocks:
            if self._ref[b] <= 0:
                raise ValueError(f"block {b} is not allocated")
            self._ref[b] += 1

    def deref(self, blocks) -> int:
        """Drop one reference per block; blocks reaching zero return to
        the free list. Returns how many were actually freed."""
        freed = 0
        for b in blocks:
            if self._ref[b] <= 0:
                raise ValueError(f"block {b} is not allocated")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._free.append(b)
                freed += 1
        self.total_freed += freed
        return freed

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def stats(self) -> dict:
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "blocks_free": self.free_blocks,
            "blocks_used": self.used_blocks,
            "total_allocated": self.total_allocated,
            "total_freed": self.total_freed,
        }
