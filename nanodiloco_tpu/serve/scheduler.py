"""Admission queue + deterministic tick loop for the serving engine.

The scheduler is the testable half of continuous batching: it owns WHICH
request runs in WHICH slot WHEN, and nothing else. The model lives
behind a three-method backend surface (``prefill(slot, request) ->
first_token``, ``step() -> [B] tokens``, ``release(slot)``), so every
scheduling decision — admission order, slot refill mid-decode, EOS
retirement, queue-full backpressure, deadline expiry — is provable with
a scripted fake backend and an injected clock, no model and no RNG
ambiguity (the same injectable-clock discipline as ``obs/watchdog.py``
and ``resilience/retry.py``).

Tick anatomy (one call, strictly ordered, deterministic):
1. expire queued requests whose deadline passed (they never held a slot);
2. admit from the FIFO queue into free slots, lowest slot index first —
   each admission prefills and may finish immediately (stop token or
   ``max_new_tokens == 1``), freeing the slot for the NEXT queued
   request within the same pass;
3. if any slot is live, ONE decode step advances them all; finished
   slots (stop token / length / deadline) are retired and their slots
   are free for the next tick's admission pass — requests join and
   leave the batch mid-stream, there is no barrier between requests.

Threading: ``submit`` may be called from any thread (the HTTP handlers);
``tick`` must be called from exactly one thread. The queue is the only
shared state and sits under a lock; everything else belongs to the tick
thread. Completion is delivered through a ``Ticket`` the submitter
waits on.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable

from nanodiloco_tpu.obs.telemetry import Histogram, nearest_rank_percentile


class QueueFull(RuntimeError):
    """Raised by ``submit`` when the admission queue is at capacity —
    the server's 429 backpressure signal."""


@dataclasses.dataclass(frozen=True)
class GenRequest:
    """One generation request. ``deadline_s`` is a RELATIVE budget from
    submission; a request past it is expired (queued) or retired with
    its partial output (running). ``request_id`` is an optional
    client-supplied correlation id echoed in the result (and stamped on
    the request's trace spans); absent, the scheduler derives one from
    its rid so client logs, serve spans, and histograms always have a
    join key."""

    prompt: tuple[int, ...]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    stop_token: int | None = None
    deadline_s: float | None = None
    request_id: str | None = None


class Ticket:
    """Handle returned by ``submit``: ``wait(timeout)`` blocks until the
    scheduler finishes the request and returns the result dict
    (``None`` on timeout). ``cancel()`` asks the scheduler to drop the
    request at its next opportunity — a queued request never takes a
    slot, a decoding one is retired with its partial output — so an
    abandoned client (HTTP timeout, disconnect) stops spending slot
    capacity on tokens nobody will read."""

    def __init__(self, rid: int) -> None:
        self.rid = rid
        self.result: dict | None = None
        self._event = threading.Event()
        self._cancelled = threading.Event()

    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> None:
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def wait(self, timeout: float | None = None) -> dict | None:
        self._event.wait(timeout)
        return self.result


@dataclasses.dataclass
class _Queued:
    ticket: Ticket
    request: GenRequest
    submitted_at: float
    deadline_at: float | None


@dataclasses.dataclass
class _Running:
    ticket: Ticket
    request: GenRequest
    submitted_at: float
    deadline_at: float | None
    admitted_at: float
    first_token_at: float
    tokens: list[int]


class Scheduler:
    """FIFO admission + slot allocation over a backend with ``num_slots``
    slots. ``clock`` is injectable (monotonic seconds)."""

    def __init__(
        self,
        backend,
        *,
        max_queue: int = 64,
        clock: Callable[[], float] = time.monotonic,
        tracer=None,
    ) -> None:
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1; got {max_queue}")
        self.backend = backend
        self._clock = clock
        # per-request span sink (obs/tracer.SpanTracer or None): the
        # scheduler reports each request's queued/prefill/decode phases
        # via record_span with ITS OWN clock's timestamps — construct
        # the tracer with the same clock callable, or the serve trace's
        # lanes won't align. Export through trace_shard_path / `report
        # merge-trace` puts serve spans on the same Perfetto timeline
        # as the training shards.
        self.tracer = tracer
        self.max_queue = int(max_queue)
        self._slots: list[_Running | None] = [None] * backend.num_slots
        self._queue: collections.deque[_Queued] = collections.deque()
        self._lock = threading.Lock()
        self._next_rid = 0
        # stats (read by the server's gauges; written by the tick thread
        # except rejected, which submit bumps under the queue lock)
        self._served = 0
        self._rejected = 0
        self._expired = 0
        self._cancelled = 0
        self._errors = 0
        self._tokens_out = 0
        self._decode_tokens = 0
        self._decode_s = 0.0
        self._ttft: collections.deque[float] = collections.deque(maxlen=512)
        # real distributions for the scrape (cumulative-bucket
        # histograms; the deque above remains for last/p50/p95 gauges):
        # TTFT submit->first-token, slot wait submit->admit, and the
        # per-tick decode latency (one compiled step for all live slots)
        self.hist_ttft = Histogram()
        self.hist_queue_wait = Histogram()
        self.hist_decode_tick = Histogram()

    # -- submission (any thread) --------------------------------------------

    def submit(self, request: GenRequest) -> Ticket:
        now = self._clock()
        with self._lock:
            if len(self._queue) >= self.max_queue:
                self._rejected += 1
                raise QueueFull(
                    f"admission queue is full ({self.max_queue} waiting)"
                )
            ticket = Ticket(self._next_rid)
            self._next_rid += 1
            deadline = (
                now + request.deadline_s
                if request.deadline_s is not None else None
            )
            self._queue.append(_Queued(ticket, request, now, deadline))
        return ticket

    # -- the tick loop (one thread) ------------------------------------------

    def tick(self) -> int:
        """One deterministic scheduling round (see module docstring).
        Returns the number of live slots after the tick, so a serving
        loop can idle when there is no work."""
        now = self._clock()
        # 1. drop queued requests whose deadline passed or whose client
        # cancelled (they never held a slot)
        dropped: list[tuple[_Queued, str]] = []
        with self._lock:
            still = collections.deque()
            for q in self._queue:
                if q.ticket.cancelled:
                    dropped.append((q, "cancelled"))
                elif q.deadline_at is not None and now >= q.deadline_at:
                    dropped.append((q, "deadline"))
                else:
                    still.append(q)
            self._queue = still
        for q, reason in dropped:
            if reason == "deadline":
                self._expired += 1
            else:
                self._cancelled += 1
            self._span("queued", q.submitted_at, now,
                       self._req_id(q.ticket, q.request), outcome=reason)
            self._finish(q.ticket, q.request, [], reason,
                         q.submitted_at, None, None, now)

        # 2. admit into free slots, FIFO, lowest slot first; a request
        # that finishes at prefill (one token / instant stop) leaves its
        # slot free for the next queued request within the same pass
        slot = 0
        while slot < len(self._slots):
            if self._slots[slot] is not None:
                slot += 1
                continue
            q = self._pop_queue()
            if q is None:
                break
            if q.ticket.cancelled:  # cancelled between sweep and pop
                self._cancelled += 1
                now2 = self._clock()
                self._span("queued", q.submitted_at, now2,
                           self._req_id(q.ticket, q.request),
                           outcome="cancelled")
                self._finish(q.ticket, q.request, [], "cancelled",
                             q.submitted_at, None, None, now2)
                continue
            rid_str = self._req_id(q.ticket, q.request)
            t_admit = self._clock()
            try:
                tok0 = self.backend.prefill(slot, q.request)
            except ValueError as e:
                # a bad REQUEST must not kill the loop; anything else
                # (OOM, a donated-then-deleted cache) propagates and
                # kills the tick loop — a broken engine must flip
                # /healthz to 503, not limp along half-alive
                self._errors += 1
                self._span("queued", q.submitted_at, t_admit, rid_str,
                           outcome="error")
                self._finish(q.ticket, q.request, [], "error",
                             q.submitted_at, None, None, self._clock(),
                             error=str(e))
                continue
            t_first = self._clock()
            self.hist_queue_wait.observe(t_admit - q.submitted_at)
            self.hist_ttft.observe(t_first - q.submitted_at)
            self._span("queued", q.submitted_at, t_admit, rid_str, slot=slot)
            self._span("prefill", t_admit, t_first, rid_str, slot=slot,
                       prompt_tokens=len(q.request.prompt))
            with self._lock:  # stats() sorts this deque from HTTP threads
                self._ttft.append(t_first - q.submitted_at)
            self._tokens_out += 1
            run = _Running(q.ticket, q.request, q.submitted_at,
                           q.deadline_at, t_admit, t_first, [tok0])
            reason = self._finish_reason(run, t_first)
            if reason is None:
                self._slots[slot] = run
                slot += 1
            else:
                # prefill already activated the slot in the backend; an
                # unreleased instant-finish would decode as a zombie
                self._backend_release(slot)
                self._retire(run, reason, t_first)

        # 3. one decode step for everyone live
        live = [s for s in range(len(self._slots)) if self._slots[s] is not None]
        if live:
            t0 = self._clock()
            toks = self.backend.step()
            t1 = self._clock()
            self._decode_s += t1 - t0
            self.hist_decode_tick.observe(t1 - t0)
            self._tokens_out += len(live)
            self._decode_tokens += len(live)
            for s in live:
                run = self._slots[s]
                run.tokens.append(int(toks[s]))
                reason = self._finish_reason(run, t1)
                if reason is not None:
                    self._backend_release(s)
                    self._slots[s] = None
                    self._span("decode", run.first_token_at, t1,
                               self._req_id(run.ticket, run.request),
                               tokens=len(run.tokens), outcome=reason)
                    self._retire(run, reason, t1)
        return sum(1 for s in self._slots if s is not None)

    def _req_id(self, ticket: Ticket, request: GenRequest) -> str:
        """The request's correlation id: client-supplied when present,
        else derived from the scheduler's rid — the SAME string lands in
        the result dict, the HTTP response, and the trace spans."""
        return request.request_id or f"req-{ticket.rid}"

    def _span(self, name: str, t0: float, t1: float, request_id: str,
              **args) -> None:
        if self.tracer is not None:
            self.tracer.record_span(
                name, t0, t1, request_id=request_id, **args
            )

    def _backend_release(self, slot: int) -> None:
        release = getattr(self.backend, "release", None)
        if release is not None:
            release(slot)

    def _pop_queue(self) -> _Queued | None:
        with self._lock:
            return self._queue.popleft() if self._queue else None

    def _finish_reason(self, run: _Running, now: float) -> str | None:
        req = run.request
        if req.stop_token is not None and run.tokens[-1] == req.stop_token:
            return "stop"
        if len(run.tokens) >= req.max_new_tokens:
            return "length"
        if run.ticket.cancelled:
            return "cancelled"
        if run.deadline_at is not None and now >= run.deadline_at:
            return "deadline"
        return None

    def _retire(self, run: _Running, reason: str, now: float) -> None:
        if reason == "cancelled":
            self._cancelled += 1
        else:
            self._served += 1
        self._finish(run.ticket, run.request, run.tokens, reason,
                     run.submitted_at, run.admitted_at, run.first_token_at,
                     now)

    def _finish(self, ticket: Ticket, request: GenRequest, tokens: list[int],
                reason: str, submitted_at: float, admitted_at: float | None,
                first_token_at: float | None, now: float,
                error: str | None = None) -> None:
        result = {
            "rid": ticket.rid,
            "request_id": self._req_id(ticket, request),
            "tokens": list(tokens),
            "finish_reason": reason,
            # time spent WAITING for a slot (a never-admitted request
            # waited its whole life); ttft additionally includes prefill
            "queued_s": (
                (admitted_at if admitted_at is not None else now)
                - submitted_at
            ),
            "ttft_s": (
                first_token_at - submitted_at
                if first_token_at is not None else None
            ),
            "decode_s": (
                now - first_token_at if first_token_at is not None else 0.0
            ),
            "total_s": now - submitted_at,
        }
        if error is not None:
            result["error"] = error
        ticket.result = result
        ticket._event.set()

    # -- observability -------------------------------------------------------

    def queue_depth(self) -> int:
        """Cheap accessor for the serving loop's idle check."""
        with self._lock:
            return len(self._queue)

    def stats(self) -> dict:
        """Snapshot for the serve gauges. TTFT percentiles come from a
        rolling window of the last 512 admissions, by the standard
        nearest-rank definition (``nearest_rank_percentile`` — the
        previous ``int(p*len)`` index was biased at small n: p50 of
        [1,2] read 2, p95 of 20 samples read the max, not the 19th)."""
        with self._lock:
            depth = len(self._queue)
            ttft_snapshot = list(self._ttft)  # tick appends under the lock
        ttft = sorted(ttft_snapshot)

        def pct(p: float) -> float | None:
            return nearest_rank_percentile(ttft, p)

        return {
            "queue_depth": depth,
            "slots_busy": sum(1 for s in self._slots if s is not None),
            "slots_total": len(self._slots),
            "served": self._served,
            "rejected": self._rejected,
            "expired": self._expired,
            "cancelled": self._cancelled,
            "errors": self._errors,
            "tokens_out": self._tokens_out,
            "decode_s": self._decode_s,
            "decode_tokens_per_sec": (
                self._decode_tokens / self._decode_s
                if self._decode_s > 0 else None
            ),
            "ttft_last_s": ttft_snapshot[-1] if ttft_snapshot else None,
            "ttft_p50_s": pct(0.50),
            "ttft_p95_s": pct(0.95),
            # full distributions (cumulative-bucket form) for the
            # histogram families on /metrics
            "hist_ttft": self.hist_ttft.snapshot(),
            "hist_queue_wait": self.hist_queue_wait.snapshot(),
            "hist_decode_tick": self.hist_decode_tick.snapshot(),
        }
