"""SLO-aware admission + deterministic tick loop for the serving engine.

The scheduler is the testable half of continuous batching: it owns WHICH
request runs in WHICH slot WHEN, and nothing else. The model lives
behind a small backend surface (``start_prefill(slot, request) ->
chunks_pending``, ``prefill_step(slot) -> first_token | None``,
``step() -> [B] tokens``, ``release(slot)``), so every scheduling
decision — admission order, chunk interleaving, slot refill mid-decode,
EOS retirement, queue-full backpressure, deadline expiry, starvation
boosts — is provable with a scripted fake backend and an injected clock,
no model and no RNG ambiguity (the same injectable-clock discipline as
``obs/watchdog.py`` and ``resilience/retry.py``).

Admission is deadline/priority ordered, not FIFO (the deadline machinery
existed since PR 4 but only triggered expiry): among queued requests the
scheduler picks the lowest ``priority`` class first (0 = most urgent)
and earliest deadline within a class (EDF; deadline-less requests sort
last, then submit order breaks ties). One bound keeps best-effort
traffic live: a request queued longer than ``starvation_s`` is admitted
next regardless of class, so a stream of urgent work can delay
best-effort requests but never starve them forever.

Prefill is CHUNKED (Sarathi-Serve, arXiv:2403.02310): admission stages a
request into its slot; each tick then runs AT MOST ONE prefill chunk,
between decode ticks, so a 4k-token prompt admits incrementally and
never freezes live decode streams. When several slots are mid-prefill,
the chunk goes to the fewest-chunks-remaining slot first
(shortest-remaining-first: a short prompt's single chunk never waits
behind a long prompt's fifty, which is what bounds short-request TTFT
under interference), with priority class then submit order as ties —
bounded by aging: a slot bypassed ``prefill_aging_ticks`` consecutive
ticks takes the next chunk regardless, so a steady stream of one-chunk
shorts delays a long prefill but can never starve it.

Tick anatomy (one call, strictly ordered, deterministic):
1. expire queued requests whose deadline passed (they never held a
   slot) and drop cancelled ones;
2. expire/cancel requests mid-prefill — a deadline can pass between
   chunks; the slot is released with the usual empty-result expiry;
3. admit from the queue into free slots in SLO order (above) — staging
   only, no model compute yet; a paged backend may refuse for lack of
   free KV BLOCKS (``BlocksExhausted``), which leaves the request
   queued head-of-line with nothing allocated — admission gates on
   blocks as well as slots, and the stall is counted per cause;
4. run ONE prefill chunk for the neediest mid-prefill slot; a final
   chunk yields the request's first token (it may also finish it
   outright: stop token or ``max_new_tokens == 1``);
5. if any slot is decoding, ONE decode step advances them all; the
   backend returns a token VECTOR per slot (one token without
   speculation, up to k+1 with it — never zero), delivered in order
   with the stop token and length bound scanned WITHIN the vector;
   finished slots (stop token / length / deadline) are retired and
   their slots are free for the next tick's admission pass — requests
   join and leave the batch mid-stream, there is no barrier between
   requests. Decode stats count EMITTED tokens, not ticks.

Threading: ``submit`` may be called from any thread (the HTTP handlers);
``tick`` must be called from exactly one thread. The queue is the only
shared state and sits under a lock; everything else belongs to the tick
thread. Completion is delivered through a ``Ticket`` the submitter
waits on.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable

import numpy as np

from nanodiloco_tpu.obs import flightrec
from nanodiloco_tpu.obs.telemetry import Histogram, nearest_rank_percentile
from nanodiloco_tpu.obs.tracer import TraceContext
from nanodiloco_tpu.serve.block_pool import BlocksExhausted


class QueueFull(RuntimeError):
    """Raised by ``submit`` when the admission queue is at capacity —
    the server's 429 backpressure signal. The message names WHAT the
    queue is stuck behind (no free slot vs no free KV blocks) so a 429
    distinguishes slot-bound from HBM-bound saturation."""


class ClassShed(QueueFull):
    """Raised by ``submit`` when the request's priority class is above
    the current admission ceiling (``set_admission_max_priority``) —
    overload shedding, NOT backpressure. The distinction matters on the
    wire: a busy 429 means "this replica, right now" and the fleet
    router retries another replica; a shed 429 means "this CLASS, fleet
    policy" and retrying elsewhere would pointlessly hammer every
    replica — the server marks it ``"shed": true`` so the router
    propagates it terminally."""

    def __init__(self, shed_class: int, max_priority: int) -> None:
        super().__init__(
            f"priority class {shed_class} is shed under overload "
            f"(admitting classes 0..{max_priority})"
        )
        self.shed_class = int(shed_class)
        self.max_priority = int(max_priority)


@dataclasses.dataclass(frozen=True)
class GenRequest:
    """One generation request. ``deadline_s`` is a RELATIVE budget from
    submission; a request past it is expired (queued or mid-prefill) or
    retired with its partial output (decoding). ``priority`` is the SLO
    class (0 = most urgent; admission is EDF within a class; default 1
    = normal, best-effort traffic should use a higher number).
    ``prefix_cache`` opts this request out of shared-prefix KV reuse
    (both reading and populating) when False. ``speculate`` opts this
    request out of speculative decoding when False (it decodes one
    token per tick even on an engine with ``spec_k > 0``; greedy and
    sampled streams are bit-identical either way — the opt-out is a
    latency/fairness knob, not a correctness one). ``request_id`` is an
    optional client-supplied correlation id echoed in the result (and
    stamped on the request's trace spans); absent, the scheduler
    derives one from its rid so client logs, serve spans, and
    histograms always have a join key. ``prefill_only`` is the
    disaggregated-serving admission mode (fleet/disagg.py): the request
    finishes at its FIRST token with ``finish_reason="prefilled"`` and
    its slot is PARKED — cache rows intact, not decoding — until
    ``/admin/kv/export`` ships them to a decode replica (or the park
    TTL/deadline reclaims the slot)."""

    prompt: tuple[int, ...]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    stop_token: int | None = None
    deadline_s: float | None = None
    request_id: str | None = None
    priority: int = 1
    prefix_cache: bool = True
    speculate: bool = True
    prefill_only: bool = False
    # causal trace context in wire form (obs/tracer.TraceContext): the
    # router's per-attempt span id — this request's queued/prefill/
    # decode spans parent under it, so a fleet trace stitches into one
    # tree. None = untraced (solo clients, old routers).
    trace_context: str | None = None


class Ticket:
    """Handle returned by ``submit``: ``wait(timeout)`` blocks until the
    scheduler finishes the request and returns the result dict
    (``None`` on timeout). ``cancel()`` asks the scheduler to drop the
    request at its next opportunity — a queued or mid-prefill request
    never decodes, a decoding one is retired with its partial output —
    so an abandoned client (HTTP timeout, disconnect) stops spending
    slot capacity on tokens nobody will read."""

    def __init__(self, rid: int) -> None:
        self.rid = rid
        self.result: dict | None = None
        self._event = threading.Event()
        self._cancelled = threading.Event()

    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> None:
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def wait(self, timeout: float | None = None) -> dict | None:
        self._event.wait(timeout)
        return self.result


class ControlHandle:
    """Handle for a function handed to the tick thread via
    ``Scheduler.call_on_tick``: ``wait(timeout)`` blocks until the tick
    loop has run it (returns True), then ``result``/``error`` carry the
    outcome. Exists because the engine belongs to the tick thread — a
    weight hot-swap arriving over HTTP must run BETWEEN ticks, never
    concurrently with a compiled dispatch."""

    def __init__(self, fn: Callable[[], object]) -> None:
        self.fn = fn
        self.result: object | None = None
        self.error: str | None = None
        self._event = threading.Event()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)


@dataclasses.dataclass
class _Queued:
    ticket: Ticket
    request: GenRequest
    submitted_at: float
    deadline_at: float | None


@dataclasses.dataclass
class _Prefilling:
    """A slot whose request is staged but still prefilling in chunks.
    ``bypassed`` counts consecutive ticks the SRPT pick went elsewhere —
    the aging input that keeps a long prefill from starving."""

    ticket: Ticket
    request: GenRequest
    submitted_at: float
    deadline_at: float | None
    admitted_at: float
    chunks_left: int
    chunks_run: int = 0
    bypassed: int = 0
    # device-time attribution (obs/devtime): measured chunk seconds
    # billed wholly to this request, and the KV blocks it holds (a
    # paged allocation is all-or-nothing at admission) for the
    # block-seconds bill at release
    prefill_device_s: float = 0.0
    blocks_held: int = 0


@dataclasses.dataclass
class _Parked:
    """A prefilled stream whose slot is held for KV export (the
    disaggregated handoff window). The ticket already finished — with
    ``finish_reason="prefilled"`` and the first token — so nothing
    waits on this; the slot's cache rows survive until
    ``export_parked`` ships them, or the deadline/park-TTL sweep
    reclaims an abandoned handoff."""

    request: GenRequest
    request_id: str
    tokens: list[int]
    submitted_at: float
    deadline_at: float | None
    admitted_at: float
    parked_at: float
    prefill_device_s: float = 0.0
    blocks_held: int = 0


@dataclasses.dataclass
class _Running:
    ticket: Ticket
    request: GenRequest
    submitted_at: float
    deadline_at: float | None
    admitted_at: float
    first_token_at: float
    tokens: list[int]
    # device-time attribution: prefill seconds carried over from the
    # _Prefilling phase; decode seconds are this slot's share of each
    # measured tick (split over the slots it advanced, weighted by
    # emitted positions — ISSUE 17's apportionment rule)
    prefill_device_s: float = 0.0
    decode_device_s: float = 0.0
    blocks_held: int = 0


class Scheduler:
    """SLO-ordered admission + slot allocation over a backend with
    ``num_slots`` slots. ``clock`` is injectable (monotonic seconds);
    ``starvation_s`` bounds how long priority traffic may delay a
    best-effort request (None = pure priority/EDF, starvable)."""

    def __init__(
        self,
        backend,
        *,
        max_queue: int = 64,
        clock: Callable[[], float] = time.monotonic,
        tracer=None,
        starvation_s: float | None = 30.0,
        prefill_aging_ticks: int = 8,
        park_ttl_s: float = 30.0,
    ) -> None:
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1; got {max_queue}")
        if starvation_s is not None and starvation_s <= 0:
            raise ValueError(
                f"starvation_s must be positive or None; got {starvation_s}"
            )
        if prefill_aging_ticks < 1:
            raise ValueError(
                f"prefill_aging_ticks must be >= 1; got {prefill_aging_ticks}"
            )
        if park_ttl_s <= 0:
            raise ValueError(f"park_ttl_s must be > 0; got {park_ttl_s}")
        self.backend = backend
        # how long a prefilled slot may sit parked awaiting KV export
        # before the sweep reclaims it (a crashed/partitioned router
        # must not leak slots and blocks through abandoned handoffs)
        self.park_ttl_s = float(park_ttl_s)
        self._clock = clock
        # in-slot aging bound for the per-tick chunk pick (step 4): a
        # mid-prefill slot bypassed this many consecutive ticks gets
        # the next chunk regardless of shortest-remaining-first
        self.prefill_aging_ticks = int(prefill_aging_ticks)
        # per-request span sink (obs/tracer.SpanTracer or None): the
        # scheduler reports each request's queued/prefill/decode phases
        # via record_span with ITS OWN clock's timestamps — construct
        # the tracer with the same clock callable, or the serve trace's
        # lanes won't align. Export through trace_shard_path / `report
        # merge-trace` puts serve spans on the same Perfetto timeline
        # as the training shards.
        self.tracer = tracer
        self.max_queue = int(max_queue)
        self.starvation_s = starvation_s
        self._slots: list[_Prefilling | _Running | _Parked | None] = (
            [None] * backend.num_slots
        )
        self._queue: collections.deque[_Queued] = collections.deque()
        self._lock = threading.Lock()
        self._next_rid = 0
        # drain state (fleet weight pushes): True stops ADMISSION only —
        # queued requests stay queued (deadlines still expire them),
        # in-flight prefills and streams run to completion. The serving
        # replica reports not-READY while draining but stays LIVE: the
        # router must stop routing to it, not eject it as dead.
        self._draining = False
        # control queue: functions other threads hand to the tick thread
        # (weight swaps mutate the engine, which is single-threaded by
        # construction); run at the top of the next tick
        self._control: collections.deque[ControlHandle] = collections.deque()
        # stats (read by the server's gauges; written by the tick thread
        # except rejected, which submit bumps under the queue lock)
        self._served = 0
        self._rejected = 0
        self._expired = 0
        self._cancelled = 0
        self._errors = 0
        # parked slots reclaimed without export (disagg handoffs the
        # router abandoned — TTL or deadline fired before /admin/kv/export)
        self._park_expired = 0
        # class-aware overload shedding: requests whose priority is
        # ABOVE this ceiling are refused at submit (ClassShed -> a
        # terminal 429) so the highest classes' SLO holds while load
        # exceeds capacity. 9 admits every class (the priority range is
        # 0..9); the fleet router / autoscaler lowers it under
        # forecasted exhaustion via /admin/admission.
        self._admission_max_priority = 9
        self._shed_by_priority: dict[int, int] = {}
        # admission-stall accounting: ticks on which the next queued
        # request could not be admitted, split by WHY — every slot
        # occupied ("no_slot") vs the backend's KV block pool unable to
        # hold the request right now ("no_blocks"). The split is what
        # tells an operator whether to add slots or HBM.
        self._blocked_no_slot = 0
        self._blocked_no_blocks = 0
        self._tokens_out = 0
        self._decode_tokens = 0
        self._decode_s = 0.0
        self._prefill_chunks = 0   # chunks run (counter)
        # device-time and cost attribution (obs/devtime): measured
        # prefill-dispatch seconds (the decode twin is _decode_s), the
        # per-class device-second and KV-block-second rollups the
        # billing counters export, and the two decode-tick windows the
        # interference ratio derives from — tick p50 with vs without
        # pending prefill chunks, the DistServe tier-split signal
        # (arXiv:2401.09670; ROADMAP item 1)
        self._prefill_s = 0.0
        self._device_s_by_priority: dict[int, float] = {}
        self._kv_block_s_by_priority: dict[int, float] = {}
        self._tick_with_prefill: collections.deque[float] = (
            collections.deque(maxlen=512)
        )
        self._tick_no_prefill: collections.deque[float] = (
            collections.deque(maxlen=512)
        )
        self._ttft: collections.deque[float] = collections.deque(maxlen=512)
        # per-class TTFT windows: the gauge the highest class's SLO rule
        # alerts on — the fleet-wide TTFT p95 is meaningless under
        # class-aware shedding (it mixes the protected class with the
        # best-effort one being sacrificed)
        self._ttft_by_priority: dict[int, collections.deque] = {}
        # real distributions for the scrape (cumulative-bucket
        # histograms; the deque above remains for last/p50/p95 gauges):
        # TTFT submit->first-token, slot wait submit->admit (overall AND
        # split by priority class — the per-class wait is what an SLO
        # dashboard actually alerts on), and the per-tick decode latency
        self.hist_ttft = Histogram()
        self.hist_queue_wait = Histogram()
        self.hist_decode_tick = Histogram()
        self.hist_queue_wait_by_priority: dict[int, Histogram] = {}

    # -- submission (any thread) --------------------------------------------

    def submit(self, request: GenRequest) -> Ticket:
        now = self._clock()
        with self._lock:
            if request.priority > self._admission_max_priority:
                self._shed_by_priority[request.priority] = (
                    self._shed_by_priority.get(request.priority, 0) + 1
                )
                raise ClassShed(request.priority,
                                self._admission_max_priority)
            if len(self._queue) >= self.max_queue:
                self._rejected += 1
                raise QueueFull(
                    f"admission queue is full ({self.max_queue} waiting"
                    f"{self._saturation_detail()})"
                )
            ticket = Ticket(self._next_rid)
            self._next_rid += 1
            deadline = (
                now + request.deadline_s
                if request.deadline_s is not None else None
            )
            self._queue.append(_Queued(ticket, request, now, deadline))
        return ticket

    # -- drain + tick-thread control (any thread) ----------------------------

    def drain(self) -> None:
        """Stop admitting queued requests (in-flight streams finish;
        the queue keeps accepting submissions and keeps expiring
        deadlines). The replica's /readyz flips not-ready so the fleet
        router routes around it during a weight push."""
        self._draining = True

    def resume(self) -> None:
        self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def admission_max_priority(self) -> int:
        return self._admission_max_priority

    def set_admission_max_priority(self, max_priority: int) -> int:
        """Set the class-shedding ceiling: requests with ``priority >
        max_priority`` are refused with ``ClassShed`` (a terminal 429)
        until the ceiling is raised again. 9 admits everything; 0 sheds
        all but the most urgent class; -1 (the floor) sheds even class
        0 — a full admission stop that, unlike ``drain``, answers with
        an honest shed body instead of flipping readiness."""
        if not isinstance(max_priority, int) or isinstance(
                max_priority, bool) or not -1 <= max_priority <= 9:
            raise ValueError(
                f"max_priority must be an integer in [-1, 9]; got "
                f"{max_priority!r}"
            )
        self._admission_max_priority = max_priority
        return max_priority

    def in_flight(self) -> int:
        """Slots holding a request (prefilling or decoding) — what a
        drain waits on before a weight push proceeds."""
        return sum(1 for s in self._slots if s is not None)

    def call_on_tick(self, fn: Callable[[], object]) -> ControlHandle:
        """Schedule ``fn`` onto the tick thread (run before the next
        tick's scheduling passes). The returned handle carries the
        result — or the error: a control function raising must report
        to ITS caller, never kill the serving loop."""
        handle = ControlHandle(fn)
        with self._lock:
            self._control.append(handle)
        return handle

    # -- KV shipping (disaggregated serving; run via call_on_tick) -----------

    def export_parked(self, request_id: str,
                      trace_context: str | None = None):
        """Ship a PARKED request's raw KV out and free its slot. Tick
        thread only (hand it over with ``call_on_tick``). Returns
        ``(raw_export, parked)`` — the backend's ``export_kv`` dict plus
        the parked record (cursor, emitted tokens, original request) —
        or ``None`` when no parked slot matches (expired, already
        exported, or never here: the server's 404). ``trace_context``
        is the router's export-leg wire context; the ``kv_export`` span
        parents under it."""
        t0 = self._clock()
        ctx = None
        if self.tracer is not None and trace_context:
            wire = TraceContext.from_wire(trace_context)
            ctx = wire.child() if wire is not None else None
        for s, run in enumerate(self._slots):
            if isinstance(run, _Parked) and run.request_id == request_id:
                raw = self.backend.export_kv(s)
                self._backend_release(s)
                self._slots[s] = None
                self._span("kv_export", t0, self._clock(), request_id,
                           ctx=ctx, slot=s, outcome="ok")
                return raw, run
        self._span("kv_export", t0, self._clock(), request_id,
                   ctx=ctx, outcome="missing")
        return None

    def _import_ctx(self, request: GenRequest):
        """The kv_import span's context: a child of the router's
        import-leg wire context (rides in the shipped request spec)."""
        if self.tracer is None or not request.trace_context:
            return None
        wire = TraceContext.from_wire(request.trace_context)
        return wire.child() if wire is not None else None

    def admit_import(self, request: GenRequest, shipped) -> Ticket:
        """Admit a SHIPPED stream straight into a free slot, bypassing
        the queue: the prompt is already prefilled — its KV rows arrive
        in ``shipped`` — so the slot goes directly to ``_Running`` and
        the next decode tick resumes the stream mid-request. Tick
        thread only (``call_on_tick``); the HTTP handler maps the
        raises: ``ShipMismatchError`` -> 409, ``BlocksExhausted`` /
        ``QueueFull`` -> 429, anything else -> 400."""
        t0 = self._clock()
        imp_ctx = self._import_ctx(request)
        # shipped requests carry the router's correlation id; "(ship)"
        # only when a direct caller omitted one (no ticket exists yet)
        rid = request.request_id or "(ship)"
        slot = next(
            (s for s in range(len(self._slots)) if self._slots[s] is None),
            None,
        )
        if slot is None:
            self._span("kv_import", t0, self._clock(), rid,
                       ctx=imp_ctx, outcome="busy")
            raise QueueFull(
                "no free KV import slot"
                f"{self._saturation_detail()}"
            )
        with self._lock:
            ticket = Ticket(self._next_rid)
            self._next_rid += 1
        now = self._clock()
        try:
            # raises ShipMismatchError / ShipFormatError / BlocksExhausted /
            # ValueError having allocated nothing (all-or-nothing import)
            self.backend.import_kv(slot, request, shipped)
        except Exception:
            self._span("kv_import", t0, self._clock(), rid,
                       ctx=imp_ctx, outcome="error")
            raise
        self._span("kv_import", t0, self._clock(), rid,
                   ctx=imp_ctx, slot=slot, outcome="ok")
        held = getattr(self.backend, "blocks_held", None)
        deadline = (
            now + request.deadline_s
            if request.deadline_s is not None else None
        )
        run = _Running(
            ticket, request, now, deadline, now, now,
            [int(t) for t in shipped.emitted],
            blocks_held=int(held(slot)) if held is not None else 0,
        )
        # a ship can arrive already satisfied (stop token in the emitted
        # tail, or emitted == max_new_tokens): retire instantly rather
        # than decode a finished stream
        reason = self._finish_reason(run, now)
        if reason is not None:
            self._backend_release(slot)
            self._retire(run, reason, now)
        else:
            self._slots[slot] = run
        return ticket

    # -- the tick loop (one thread) ------------------------------------------

    def tick(self) -> int:
        """One deterministic scheduling round (see module docstring).
        Returns the number of occupied slots (prefilling or decoding)
        after the tick, so a serving loop can idle when there is no
        work."""
        # 0. run control functions handed over from other threads (a
        # weight hot-swap): they mutate the backend, which belongs to
        # this thread; an error is the CALLER's to read, never fatal to
        # the serving loop
        while True:
            with self._lock:
                if not self._control:
                    break
                handle = self._control.popleft()
            try:
                handle.result = handle.fn()
            except Exception as e:
                handle.error = f"{type(e).__name__}: {e}"
            handle._event.set()
        now = self._clock()
        # 1. drop queued requests whose deadline passed or whose client
        # cancelled (they never held a slot)
        dropped: list[tuple[_Queued, str]] = []
        with self._lock:
            still = collections.deque()
            for q in self._queue:
                if q.ticket.cancelled:
                    dropped.append((q, "cancelled"))
                elif q.deadline_at is not None and now >= q.deadline_at:
                    dropped.append((q, "deadline"))
                else:
                    still.append(q)
            self._queue = still
        for q, reason in dropped:
            if reason == "deadline":
                self._expired += 1
            else:
                self._cancelled += 1
            self._span("queued", q.submitted_at, now,
                       self._req_id(q.ticket, q.request),
                       ctx=self._ctx(q.request), outcome=reason)
            self._finish(q.ticket, q.request, [], reason,
                         q.submitted_at, None, None, now)

        # 2. expire/cancel requests caught mid-prefill: a deadline can
        # pass between two chunks of a long prompt; the slot frees with
        # the same empty-result expiry a queued request gets
        for s, run in enumerate(self._slots):
            if not isinstance(run, _Prefilling):
                continue
            if run.ticket.cancelled:
                reason = "cancelled"
                self._cancelled += 1
            elif run.deadline_at is not None and now >= run.deadline_at:
                reason = "deadline"
                self._expired += 1
            else:
                continue
            self._backend_release(s)
            self._slots[s] = None
            self._span("prefill", run.admitted_at, now,
                       self._req_id(run.ticket, run.request),
                       ctx=self._ctx(run.request), slot=s,
                       chunks=run.chunks_run, outcome=reason)
            # chunks already run billed their seconds to this request —
            # an expiry mid-prefill must not drop them (no second
            # silently vanishes), and the blocks it held settle here
            self._finish(run.ticket, run.request, [], reason,
                         run.submitted_at, run.admitted_at, None, now,
                         prefill_device_s=run.prefill_device_s,
                         kv_block_seconds=(
                             run.blocks_held * (now - run.admitted_at)))

        # 2b. reclaim PARKED slots whose handoff was abandoned: the
        # ticket already finished ("prefilled"), so this is pure
        # resource recovery — a router that crashed or partitioned
        # between prefill and export must not leak the slot and its KV
        # blocks forever
        for s, run in enumerate(self._slots):
            if not isinstance(run, _Parked):
                continue
            if ((run.deadline_at is not None and now >= run.deadline_at)
                    or now - run.parked_at >= self.park_ttl_s):
                self._backend_release(s)
                self._slots[s] = None
                self._park_expired += 1

        # 3. admit into free slots in SLO order (priority class, EDF
        # within it, starvation bound on top) — staging only; the model
        # work happens one chunk per tick in step 4. A cancelled or
        # invalid PEEK retries the SAME free slot with the next queued
        # request: a dud at the queue head must not cost a viable
        # request its admission tick. Admission gates on KV BLOCKS as
        # well as slots: a backend that cannot currently hold the
        # request's cache raises ``BlocksExhausted`` having allocated
        # NOTHING — the request is left queued (head-of-line, so SLO
        # order is preserved; blocks free as live requests retire) and
        # the stall is counted under its own reason.
        slot = 0
        blocked_on_blocks = False
        # a draining scheduler admits NOTHING (the whole point of the
        # drain: in-flight streams finish, the queue holds) — and the
        # stall counters stay quiet: a drain is an operator action, not
        # a capacity signal
        while not self._draining and slot < len(self._slots):
            if self._slots[slot] is not None:
                slot += 1
                continue
            q = self._peek_queued()
            if q is None:
                break
            if q.ticket.cancelled:  # cancelled between sweep and peek
                self._dequeue(q)
                self._cancelled += 1
                now2 = self._clock()
                self._span("queued", q.submitted_at, now2,
                           self._req_id(q.ticket, q.request),
                           ctx=self._ctx(q.request), outcome="cancelled")
                self._finish(q.ticket, q.request, [], "cancelled",
                             q.submitted_at, None, None, now2)
                continue
            rid_str = self._req_id(q.ticket, q.request)
            t_admit = self._clock()
            try:
                chunks = int(self.backend.start_prefill(slot, q.request))
            except BlocksExhausted:
                # nothing was allocated (the pool's alloc is
                # all-or-nothing) and the request stays exactly where
                # it was in the queue — retried next tick
                blocked_on_blocks = True
                self._blocked_no_blocks += 1
                break
            except ValueError as e:
                # a bad REQUEST must not kill the loop; anything else
                # (OOM, a donated-then-deleted cache) propagates and
                # kills the tick loop — a broken engine must flip
                # /healthz to 503, not limp along half-alive
                self._dequeue(q)
                self._errors += 1
                self._span("queued", q.submitted_at, t_admit, rid_str,
                           ctx=self._ctx(q.request), outcome="error")
                self._finish(q.ticket, q.request, [], "error",
                             q.submitted_at, None, None, self._clock(),
                             error=str(e))
                continue
            self._dequeue(q)
            wait = t_admit - q.submitted_at
            # exemplar: the sampled trace id rides into whichever bucket
            # this observation lands in, linking the histogram back to
            # one real request's causal tree
            self.hist_queue_wait.observe(
                wait, exemplar=self._trace_id(q.request))
            self._priority_hist(q.request.priority).observe(wait)
            self._span("queued", q.submitted_at, t_admit, rid_str,
                       ctx=self._ctx(q.request), slot=slot,
                       priority=q.request.priority)
            # KV blocks the admission just allocated (all-or-nothing,
            # constant until release): the block-seconds bill is
            # blocks x held-time, settled at release. Backends without
            # the accessor (dense, fakes) bill zero.
            held = getattr(self.backend, "blocks_held", None)
            self._slots[slot] = _Prefilling(
                q.ticket, q.request, q.submitted_at, q.deadline_at,
                t_admit, chunks,
                blocks_held=int(held(slot)) if held is not None else 0,
            )
            slot += 1
        if (not self._draining and not blocked_on_blocks
                and self.queue_depth() > 0
                and all(s is not None for s in self._slots)):
            self._blocked_no_slot += 1

        # 4. ONE prefill chunk, to the fewest-chunks-remaining slot
        # (shortest-remaining-first bounds short-request TTFT while a
        # long prefill is in flight), priority then admission order as
        # tie-breaks. Aging caps the delay: a slot bypassed
        # ``prefill_aging_ticks`` consecutive ticks takes the next
        # chunk regardless of SRPT — without it, a steady stream of
        # one-chunk shorts would starve a long prefill forever (the
        # admission-level starvation bound stops at the queue pop; this
        # is its in-slot counterpart).
        pf_slots = [
            s for s, r in enumerate(self._slots)
            if isinstance(r, _Prefilling)
        ]
        if pf_slots:
            aged = [s for s in pf_slots
                    if self._slots[s].bypassed >= self.prefill_aging_ticks]
            if aged:
                s = max(aged, key=lambda i: (self._slots[i].bypassed,
                                             -self._slots[i].ticket.rid))
            else:
                s = min(pf_slots, key=lambda i: (
                    self._slots[i].chunks_left,
                    self._slots[i].request.priority,
                    self._slots[i].ticket.rid,
                ))
            for other in pf_slots:
                if other != s:
                    self._slots[other].bypassed += 1
            run = self._slots[s]
            run.bypassed = 0
            # the chunk's measured seconds bill WHOLLY to this request
            # (one chunk advances exactly one prefill) — the scheduler's
            # own clock, so scripted backends and injected clocks in
            # tests attribute the same way the engine path does
            t_pf0 = self._clock()
            tok0 = self.backend.prefill_step(s)
            pf_dt = self._clock() - t_pf0
            self._prefill_s += pf_dt
            run.prefill_device_s += pf_dt
            self._prefill_chunks += 1
            run.chunks_run += 1
            run.chunks_left = max(0, run.chunks_left - 1)
            if tok0 is not None:
                t_first = self._clock()
                rid_str = self._req_id(run.ticket, run.request)
                self.hist_ttft.observe(t_first - run.submitted_at,
                                       exemplar=self._trace_id(run.request))
                self._span("prefill", run.admitted_at, t_first, rid_str,
                           ctx=self._ctx(run.request),
                           slot=s, prompt_tokens=len(run.request.prompt),
                           chunks=run.chunks_run)
                with self._lock:  # stats() sorts this deque from HTTP threads
                    self._ttft.append(t_first - run.submitted_at)
                    dq = self._ttft_by_priority.setdefault(
                        int(run.request.priority),
                        collections.deque(maxlen=256),
                    )
                    dq.append(t_first - run.submitted_at)
                self._tokens_out += 1
                live = _Running(run.ticket, run.request, run.submitted_at,
                                run.deadline_at, run.admitted_at, t_first,
                                [int(tok0)],
                                prefill_device_s=run.prefill_device_s,
                                blocks_held=run.blocks_held)
                reason = self._finish_reason(live, t_first)
                if reason is not None:
                    # prefill already activated the slot in the backend;
                    # an unreleased instant-finish would decode as a
                    # zombie
                    self._backend_release(s)
                    self._slots[s] = None
                    self._retire(live, reason, t_first)
                elif run.request.prefill_only:
                    # disaggregated admission: the stream finishes HERE
                    # with its first token; the slot parks — cache rows
                    # intact, not decoding — until /admin/kv/export
                    # ships them (or the TTL/deadline sweep reclaims an
                    # abandoned handoff). Billing settles now: block
                    # residency DURING the park is the handoff's cost,
                    # billed at export/expiry, not to the request.
                    self._slots[s] = _Parked(
                        run.request, rid_str, [int(tok0)],
                        run.submitted_at, run.deadline_at,
                        run.admitted_at, t_first,
                        prefill_device_s=run.prefill_device_s,
                        blocks_held=run.blocks_held,
                    )
                    self._served += 1
                    self._finish(
                        run.ticket, run.request, [int(tok0)], "prefilled",
                        run.submitted_at, run.admitted_at, t_first, t_first,
                        prefill_device_s=run.prefill_device_s,
                        kv_block_seconds=(
                            run.blocks_held * (t_first - run.admitted_at)),
                    )
                else:
                    self._slots[s] = live

        # 5. one decode step for everyone live. The backend emits a
        # token VECTOR per slot (1..k+1 under speculative decoding;
        # legacy/fake backends may still return one scalar per slot):
        # tokens are delivered in order, scanning for the stop token
        # and the length bound WITHIN the vector — a draft window that
        # sails past EOS must not leak post-stop tokens into the
        # result. Decode stats count EMITTED tokens, not ticks: at one
        # token per tick the two were equal, so the old tick count was
        # latently wrong the moment multi-token emission landed.
        live = [
            s for s in range(len(self._slots))
            if isinstance(self._slots[s], _Running)
        ]
        if live:
            t0 = self._clock()
            toks = self.backend.step()
            t1 = self._clock()
            tick_dt = t1 - t0
            self._decode_s += tick_dt
            self.hist_decode_tick.observe(tick_dt)
            # interference window split: was prefill work pending while
            # this decode tick ran? (staged chunks interleave with
            # decode — the p50 gap between the two windows is the
            # DistServe tier-split sizing signal)
            if any(isinstance(r, _Prefilling) for r in self._slots):
                self._tick_with_prefill.append(tick_dt)
            else:
                self._tick_no_prefill.append(tick_dt)
            # normalize every slot's emission vector FIRST: the tick's
            # measured seconds are apportioned over the slots it
            # advanced, weighted by emitted positions (plain decode
            # emits 1 per slot — an equal split; a verify tick's wider
            # emissions carry proportionally more of the window). The
            # weights sum the shares back to exactly tick_dt — no
            # second dropped or double-billed, even when a slot
            # finishes (stop/length/deadline) inside this very tick.
            vecs: dict[int, list] = {}
            for s in live:
                vec = toks[s]
                if not isinstance(vec, (list, tuple, np.ndarray)):
                    vec = [vec]  # scalar-per-slot backends
                vecs[s] = vec
            wsum = sum(max(1, len(v)) for v in vecs.values())
            for s in live:
                run = self._slots[s]
                vec = vecs[s]
                run.decode_device_s += (
                    tick_dt * max(1, len(vec)) / wsum
                )
                req = run.request
                reason = None
                emitted = 0
                for tok in vec:
                    run.tokens.append(int(tok))
                    emitted += 1
                    if (req.stop_token is not None
                            and run.tokens[-1] == req.stop_token):
                        reason = "stop"
                        break
                    if len(run.tokens) >= req.max_new_tokens:
                        reason = "length"
                        break
                self._tokens_out += emitted
                self._decode_tokens += emitted
                if reason is None:
                    reason = self._finish_reason(run, t1)
                if reason is not None:
                    self._backend_release(s)
                    self._slots[s] = None
                    self._span("decode", run.first_token_at, t1,
                               self._req_id(run.ticket, run.request),
                               ctx=self._ctx(run.request),
                               tokens=len(run.tokens), outcome=reason)
                    self._retire(run, reason, t1)
        return sum(1 for s in self._slots if s is not None)

    def _peek_queued(self) -> _Queued | None:
        """The next request to admit, WITHOUT removing it (removal is
        ``_dequeue``, called only once admission commits — a
        block-starved request must stay queued in place). Starvation
        bound first: when the OLDEST queued request (FIFO head) has
        waited past ``starvation_s``, it goes next no matter its class.
        Otherwise lowest priority number wins; within a class, earliest
        deadline (EDF; deadline-less requests last); submit order breaks
        ties (rids are issued in submit order)."""
        now = self._clock()
        with self._lock:
            if not self._queue:
                return None
            if (
                self.starvation_s is not None
                and now - self._queue[0].submitted_at >= self.starvation_s
            ):
                return self._queue[0]
            return min(self._queue, key=lambda q: (
                q.request.priority,
                q.deadline_at if q.deadline_at is not None else float("inf"),
                q.ticket.rid,
            ))

    def _dequeue(self, q: _Queued) -> None:
        """Commit a peeked request's removal (only the tick thread ever
        removes, so the element is still present)."""
        with self._lock:
            try:
                self._queue.remove(q)
            except ValueError:  # pragma: no cover - single remover
                pass

    def _saturation_detail(self) -> str:
        """Why the system is not draining, for the 429 message: KV
        block availability when the backend pages its cache ('' for
        dense backends) — a client/operator reading the error learns
        whether the ceiling is slots or HBM."""
        kv_stats = getattr(self.backend, "kv_stats", None)
        if kv_stats is None:
            return ""
        try:
            kv = kv_stats()
        except Exception:  # pragma: no cover - defensive: message only
            return ""
        if not kv:
            return ""
        return (
            f"; KV blocks {kv['blocks_free']}/{kv['num_blocks']} free"
        )

    def _priority_hist(self, priority: int) -> Histogram:
        h = self.hist_queue_wait_by_priority.get(int(priority))
        if h is None:
            # first request of a class: insert under the lock — stats()
            # snapshots this dict from the HTTP threads, and an
            # unguarded insert mid-iteration is a RuntimeError there
            with self._lock:
                h = self.hist_queue_wait_by_priority.setdefault(
                    int(priority), Histogram()
                )
        return h

    def _req_id(self, ticket: Ticket, request: GenRequest) -> str:
        """The request's correlation id: client-supplied when present,
        else derived from the scheduler's rid — the SAME string lands in
        the result dict, the HTTP response, and the trace spans."""
        return request.request_id or f"req-{ticket.rid}"

    def _span(self, name: str, t0: float, t1: float, request_id: str,
              ctx=None, **args) -> None:
        if self.tracer is not None:
            self.tracer.record_span(
                name, t0, t1, ctx=ctx, request_id=request_id, **args
            )

    def _ctx(self, request: GenRequest) -> TraceContext | None:
        """A fresh span context for one of this request's phase spans,
        parented under the router's forwarded wire context. Each call
        mints a sibling (queued/prefill/decode sit side by side under
        the same forward span). None when untraced."""
        if self.tracer is None or not request.trace_context:
            return None
        wire = TraceContext.from_wire(request.trace_context)
        return wire.child() if wire is not None else None

    def _trace_id(self, request: GenRequest) -> str | None:
        """The SAMPLED trace id for exemplar attachment, else None —
        unsampled traces must not leak ids into the exposition."""
        if not request.trace_context:
            return None
        wire = TraceContext.from_wire(request.trace_context)
        return wire.trace_id if wire is not None and wire.sampled else None

    def _backend_release(self, slot: int) -> None:
        release = getattr(self.backend, "release", None)
        if release is not None:
            release(slot)

    def _finish_reason(self, run: _Running, now: float) -> str | None:
        req = run.request
        if req.stop_token is not None and run.tokens[-1] == req.stop_token:
            return "stop"
        if len(run.tokens) >= req.max_new_tokens:
            return "length"
        if run.ticket.cancelled:
            return "cancelled"
        if run.deadline_at is not None and now >= run.deadline_at:
            return "deadline"
        return None

    def _retire(self, run: _Running, reason: str, now: float) -> None:
        if reason == "cancelled":
            self._cancelled += 1
        else:
            self._served += 1
        self._finish(run.ticket, run.request, run.tokens, reason,
                     run.submitted_at, run.admitted_at, run.first_token_at,
                     now,
                     prefill_device_s=run.prefill_device_s,
                     decode_device_s=run.decode_device_s,
                     # blocks are allocated all-or-nothing at admission
                     # and constant until release — the block-seconds
                     # bill settles exactly here, at release time
                     kv_block_seconds=run.blocks_held * (now - run.admitted_at))

    def _finish(self, ticket: Ticket, request: GenRequest, tokens: list[int],
                reason: str, submitted_at: float, admitted_at: float | None,
                first_token_at: float | None, now: float,
                error: str | None = None,
                prefill_device_s: float = 0.0,
                decode_device_s: float = 0.0,
                kv_block_seconds: float = 0.0) -> None:
        result = {
            "rid": ticket.rid,
            "request_id": self._req_id(ticket, request),
            "tokens": list(tokens),
            "finish_reason": reason,
            # time spent WAITING for a slot (a never-admitted request
            # waited its whole life); ttft additionally includes prefill
            "queued_s": (
                (admitted_at if admitted_at is not None else now)
                - submitted_at
            ),
            "ttft_s": (
                first_token_at - submitted_at
                if first_token_at is not None else None
            ),
            "decode_s": (
                now - first_token_at if first_token_at is not None else 0.0
            ),
            "total_s": now - submitted_at,
            # attribution: THIS request's measured share of dispatch
            # seconds (prefill chunks billed whole, decode/verify ticks
            # apportioned by emitted positions) and its KV residency
            # bill (blocks x seconds held) — the per-request cost line
            "prefill_device_s": prefill_device_s,
            "decode_device_s": decode_device_s,
            "kv_block_seconds": kv_block_seconds,
        }
        if error is not None:
            result["error"] = error
        # per-class cost rollup (the billing/capacity counters): one
        # central accumulation point so every finish path — retire,
        # expiry mid-prefill, instant-finish — bills identically.
        # All-zero finishes (never-admitted drops) add nothing.
        if prefill_device_s or decode_device_s or kv_block_seconds:
            prio = int(request.priority)
            with self._lock:
                self._device_s_by_priority[prio] = (
                    self._device_s_by_priority.get(prio, 0.0)
                    + prefill_device_s + decode_device_s
                )
                if kv_block_seconds:
                    self._kv_block_s_by_priority[prio] = (
                        self._kv_block_s_by_priority.get(prio, 0.0)
                        + kv_block_seconds
                    )
        # black-box feed (obs/flightrec): one bounded event per request
        # outcome, so an engine-loop death dump shows the requests in
        # flight around the fatal tick. No-op without a recorder.
        flightrec.record_event(
            "serve_finish",
            request_id=result["request_id"], reason=reason,
            tokens=len(tokens),
            **({"error": error} if error else {}),
        )
        ticket.result = result
        ticket._event.set()

    # -- observability -------------------------------------------------------

    def queue_depth(self) -> int:
        """Cheap accessor for the serving loop's idle check."""
        with self._lock:
            return len(self._queue)

    def stats(self) -> dict:
        """Snapshot for the serve gauges. TTFT percentiles come from a
        rolling window of the last 512 admissions, by the standard
        nearest-rank definition (``nearest_rank_percentile``)."""
        with self._lock:
            depth = len(self._queue)
            ttft_snapshot = list(self._ttft)  # tick appends under the lock
            prio_hists = dict(self.hist_queue_wait_by_priority)
            ttft_by_prio = {
                p: list(dq) for p, dq in self._ttft_by_priority.items()
            }
            shed_by_prio = dict(self._shed_by_priority)
            device_s_by_prio = dict(self._device_s_by_priority)
            kv_block_s_by_prio = dict(self._kv_block_s_by_priority)
            ticks_with_pf = sorted(self._tick_with_prefill)
            ticks_no_pf = sorted(self._tick_no_prefill)
        ttft = sorted(ttft_snapshot)

        def pct(p: float) -> float | None:
            return nearest_rank_percentile(ttft, p)

        prefilling = [
            s for s in self._slots if isinstance(s, _Prefilling)
        ]
        out = {
            "queue_depth": depth,
            "slots_busy": sum(1 for s in self._slots if s is not None),
            "slots_prefilling": len(prefilling),
            # slots holding a prefilled stream awaiting KV export (the
            # disagg handoff window) + handoffs abandoned past the TTL
            "slots_parked": sum(
                1 for s in self._slots if isinstance(s, _Parked)
            ),
            "park_expired": self._park_expired,
            "slots_total": len(self._slots),
            # chunk backlog: how much staged prefill work is waiting for
            # tick interleave slots — the gauge that shows a long prompt
            # being fed through without stalling decode
            "prefill_chunks_pending": sum(p.chunks_left for p in prefilling),
            "prefill_chunks_total": self._prefill_chunks,
            "served": self._served,
            "rejected": self._rejected,
            "expired": self._expired,
            "cancelled": self._cancelled,
            "errors": self._errors,
            # the same five outcomes as ONE dict — the shape the serve
            # /metrics outcome family and the SLO error-rate rule
            # (obs/slo) consume, so the label set has a single source
            "requests_by_outcome": {
                "served": self._served,
                "rejected": self._rejected,
                "expired": self._expired,
                "cancelled": self._cancelled,
                "error": self._errors,
                # class-shed refusals are their OWN outcome, not folded
                # into "rejected": busy-rejections are capacity noise,
                # sheds are deliberate policy — an SLO error-rate rule
                # must be able to tell them apart
                "shed": sum(shed_by_prio.values()),
            },
            # class-aware overload shedding state: the ceiling and the
            # per-class shed counts (the honest 429 story — which
            # classes are being sacrificed, how often)
            "admission_max_priority": self._admission_max_priority,
            "shed_by_priority": {
                p: n for p, n in sorted(shed_by_prio.items())
            },
            # admission stalls split by cause: slots exhausted vs the
            # paged backend's KV block pool exhausted — the 429/backlog
            # diagnosis gauge pair
            "admission_blocked_no_slot": self._blocked_no_slot,
            "admission_blocked_no_blocks": self._blocked_no_blocks,
            "tokens_out": self._tokens_out,
            "decode_s": self._decode_s,
            # EMITTED decode tokens (multi-token speculative ticks
            # included), not ticks x slots — the rate a client actually
            # receives tokens at
            "decode_tokens": self._decode_tokens,
            "decode_tokens_per_sec": (
                self._decode_tokens / self._decode_s
                if self._decode_s > 0 else None
            ),
            "ttft_last_s": ttft_snapshot[-1] if ttft_snapshot else None,
            "ttft_p50_s": pct(0.50),
            "ttft_p95_s": pct(0.95),
            # per-class TTFT p95 (last 256 admissions of each class):
            # what the highest class's SLO rule watches while lower
            # classes shed
            "ttft_p95_by_priority": {
                p: nearest_rank_percentile(sorted(vals), 0.95)
                for p, vals in sorted(ttft_by_prio.items())
                if vals
            },
            # full distributions (cumulative-bucket form) for the
            # histogram families on /metrics
            "hist_ttft": self.hist_ttft.snapshot(),
            "hist_queue_wait": self.hist_queue_wait.snapshot(),
            "hist_decode_tick": self.hist_decode_tick.snapshot(),
            "hist_queue_wait_by_priority": {
                p: h.snapshot() for p, h in sorted(prio_hists.items())
            },
            # measured prefill dispatch seconds (chunk-billed; the
            # decode counterpart is decode_s above) — with decode_s,
            # the scheduler-level side of the reconciliation identity
            "prefill_device_s": self._prefill_s,
            # per-class cost counters: the billing and capacity-planning
            # rollup of per-request attribution (device-seconds consumed
            # and KV block-seconds held, by priority class)
            "device_seconds_by_priority": {
                p: round(v, 6) for p, v in sorted(device_s_by_prio.items())
            },
            "kv_block_seconds_by_priority": {
                p: round(v, 6) for p, v in sorted(kv_block_s_by_prio.items())
            },
        }
        # decode-tick interference: p50 tick time with vs without
        # staged prefill chunks pending — the DistServe-style
        # prefill/decode tier-split sizing signal (ROADMAP item 1). Two
        # scalars, not a histogram family: the ratio is the signal.
        p50_w = nearest_rank_percentile(ticks_with_pf, 0.50)
        p50_n = nearest_rank_percentile(ticks_no_pf, 0.50)
        if p50_w is not None:
            out["decode_tick_p50_with_prefill_s"] = p50_w
        if p50_n is not None:
            out["decode_tick_p50_no_prefill_s"] = p50_n
        if p50_w is not None and p50_n is not None and p50_n > 0:
            out["decode_interference_ratio"] = round(p50_w / p50_n, 4)
        # tensor-parallel degree (engines expose ``tp``; 1 = unsharded):
        # a gauge, so dashboards can tell a TP fleet member from a solo
        # replica without parsing flags. Fake/scripted backends without
        # the attribute simply omit the key.
        tp = getattr(self.backend, "tp", None)
        if tp is not None:
            out["tp_degree"] = int(tp)
        # hot-swap deployment state (fleet/): which weight generation
        # this replica serves, and whether it is draining for a push.
        # Fake/scripted backends without the attribute omit the key.
        out["draining"] = self._draining
        gen = getattr(self.backend, "deploy_generation", None)
        if gen is not None:
            out["deploy_generation"] = int(gen)
        prefix_stats = getattr(self.backend, "prefix_stats", None)
        if prefix_stats is not None:
            ps = prefix_stats()
            if ps is not None:
                out["prefix_cache"] = ps
        kv_stats = getattr(self.backend, "kv_stats", None)
        if kv_stats is not None:
            kv = kv_stats()
            if kv is not None:
                out["kv_pool"] = kv
        spec_stats = getattr(self.backend, "spec_stats", None)
        if spec_stats is not None:
            spec = spec_stats()
            if spec is not None:
                out["spec"] = spec
        # KV ship traffic (export/import requests, bytes, blocks,
        # seconds) — present only once a replica has actually shipped,
        # so non-disagg stats JSONLs are unchanged
        kvship_stats = getattr(self.backend, "kvship_stats", None)
        if kvship_stats is not None:
            ship = kvship_stats()
            if ship is not None:
                out["kvship"] = ship
        # per-program dispatch ledgers from the engine's accountant
        # (device/compile seconds by kind:bucket:layout) — fakes
        # without the accessor omit the key, same as spec/kv above
        devtime_stats = getattr(self.backend, "devtime_stats", None)
        if devtime_stats is not None:
            dt = devtime_stats()
            if dt is not None:
                out["devtime"] = dt
        return out
