"""Host-side draft proposer for speculative decoding: prompt-lookup.

Prompt-lookup (n-gram) speculation needs no second model: the draft for
a request's next few tokens is the continuation of the most recent
earlier occurrence of its current n-gram tail, searched over the
request's OWN prompt + emitted output. Repetitive traffic — templated
prompts, few-shot scaffolds, code, and the self-repeating loops greedy
decoding falls into — pays off heavily; adversarial (random) traffic
simply produces no n-gram match and therefore no drafts, so the engine
falls back to the plain one-token tick with near-zero overhead. The
same premise vLLM-style engines exploit (arXiv:2309.06180 lineage);
model-free makes it a pure win before a draft model exists.

The proposer is pure host-side bookkeeping on the tick thread (the
engine calls it between compiled dispatches), so it must be cheap:
per-slot context lists plus an incremental hash index mapping every
(n, gram) to the END position of its most recent occurrence. Append is
O(ngram levels); propose is O(ngram levels) dict lookups. Nothing here
touches jax.

Acceptance feedback drives two independent adaptive controls. SIZING:
full acceptance nudges the request's draft length up toward ``max_k``,
a zero-accept tick halves it (floor 1). GATING: a rolling per-draft
acceptance EMA below ``ACCEPT_FLOOR`` stops the slot proposing at all
— verification widens the tick, and coincidental n-gram matches on
structureless traffic accept just often enough that a
reset-on-any-accept backoff would thrash forever instead of converging
to plain decode. Suppressed slots re-probe with one cheap draft on a
SHARED cadence (``new_tick``/``PROBE_PERIOD``) so recovery stays
possible without desynchronized probes re-widening every other tick.
Per-request opt-out is the engine's concern (``GenRequest.speculate``);
a slot that opted out is simply never registered here.
"""

from __future__ import annotations

__all__ = ["PromptLookupProposer"]


class PromptLookupProposer:
    """Per-slot prompt-lookup draft state. Lifecycle mirrors a slot's:
    ``begin`` at prefill completion (prompt + first token), ``propose``
    before each speculative tick, ``observe`` with the tick's emitted
    tokens, ``feedback`` with (proposed, accepted) for adaptive k,
    ``release`` when the slot retires. Single-threaded by design (the
    engine tick thread), like the block pool."""

    # a slot whose rolling per-draft acceptance falls below this stops
    # proposing: a draft only pays when its acceptance beats the
    # verify-widening overhead, and coincidental 1-gram matches on
    # structureless traffic accept ~1/top_k of the time — well below
    # break-even, but never zero, so a reset-on-any-accept backoff
    # would thrash forever instead of converging
    ACCEPT_FLOOR = 0.35
    EMA_DECAY = 0.7
    # suppressed slots re-probe with ONE draft on a shared cadence (all
    # suppressed slots probe on the SAME tick — desynchronized probes
    # would widen a verify tick every few ticks and re-create the
    # overhead the floor exists to kill)
    PROBE_PERIOD = 16
    # fresh streams ramp k up from here on success instead of opening
    # at max_k: a lookup-hostile stream's exploration then costs narrow
    # verify ticks, and a lookup-friendly one reaches max_k within
    # max_k - START_K fully-accepted ticks
    START_K = 2

    def __init__(self, max_k: int, max_ngram: int = 3) -> None:
        if max_k < 1:
            raise ValueError(f"max_k must be >= 1; got {max_k}")
        if max_ngram < 1:
            raise ValueError(f"max_ngram must be >= 1; got {max_ngram}")
        self.max_k = int(max_k)
        self.max_ngram = int(max_ngram)
        self._ctx: dict[int, list[int]] = {}
        # per slot: (n, gram tuple) -> end position of the most recent
        # PREVIOUS occurrence. A gram is indexed only once at least one
        # token follows it, so the context's own tail is never returned
        # as its own (empty) continuation.
        self._index: dict[int, dict[tuple, int]] = {}
        self._cur_k: dict[int, int] = {}
        # per-slot rolling per-draft acceptance (optimistic start: a
        # fresh stream speculates immediately; structureless traffic
        # sinks below the floor within a few ticks)
        self._ema: dict[int, float] = {}
        self._clock = 0  # shared tick counter driving the probe cadence

    # -- slot lifecycle ------------------------------------------------------

    def begin(self, slot: int, prompt_ids, first_token: int) -> None:
        """Register a slot at prefill completion: context = prompt +
        the first sampled token, index built by replaying appends."""
        self._ctx[slot] = []
        self._index[slot] = {}
        self._cur_k[slot] = min(self.START_K, self.max_k)
        self._ema[slot] = 1.0
        self.observe(slot, list(prompt_ids) + [int(first_token)])

    def release(self, slot: int) -> None:
        self._ctx.pop(slot, None)
        self._index.pop(slot, None)
        self._cur_k.pop(slot, None)
        self._ema.pop(slot, None)

    def new_tick(self) -> None:
        """Advance the shared probe clock; the engine calls this once
        per decode tick, before asking any slot for drafts."""
        self._clock += 1

    # -- the draft loop ------------------------------------------------------

    def propose(self, slot: int, cap: int) -> list[int]:
        """Up to ``min(cap, adaptive k)`` draft tokens for ``slot``:
        the continuation of the most recent earlier occurrence of the
        longest matching n-gram tail (longest n wins — a 3-gram match
        is a far stronger signal than a 1-gram). Empty when nothing
        matches: no match, no speculation, no cost."""
        ctx = self._ctx.get(slot)
        if ctx is None:
            return []
        k = min(int(cap), self._cur_k[slot], self.max_k)
        if self._ema[slot] < self.ACCEPT_FLOOR:
            # suppressed: acceptance has not been paying for the verify
            # widening; re-probe with ONE cheap draft on the shared
            # cadence so a stream whose text turns repetitive recovers
            if self._clock % self.PROBE_PERIOD:
                return []
            k = min(k, 1)
        if k <= 0:
            return []
        idx = self._index[slot]
        for n in range(min(self.max_ngram, len(ctx)), 0, -1):
            end = idx.get((n, tuple(ctx[-n:])))
            if end is not None:
                avail = ctx[end:]
                # a RECENT match leaves fewer than k known continuation
                # tokens — cycle them: a greedy stream locked into a
                # period-p loop matches p tokens back, and wrapping
                # predicts the whole loop for any k (wrong wraps just
                # reject; the genuine prefix still accepts)
                return [avail[i % len(avail)] for i in range(k)]
        return []

    def observe(self, slot: int, emitted) -> None:
        """Append the tick's emitted tokens to the slot's context,
        indexing each gram the moment it gains a continuation."""
        ctx = self._ctx.get(slot)
        if ctx is None:
            return
        idx = self._index[slot]
        for tok in emitted:
            p = len(ctx)
            for n in range(1, self.max_ngram + 1):
                if p - n >= 0:
                    idx[(n, tuple(ctx[p - n:p]))] = p
            ctx.append(int(tok))

    def feedback(self, slot: int, proposed: int, accepted: int) -> None:
        """Adaptive draft budget: full acceptance grows the slot's k by
        one (capped at max_k), a zero-accept tick halves it (floor 1),
        partial acceptance holds steady. Independently, the rolling
        per-draft acceptance EMA decides whether the slot proposes AT
        ALL (see ``ACCEPT_FLOOR``): sizing and gating are separate —
        a stream can deserve short drafts without deserving none."""
        if slot not in self._cur_k or proposed <= 0:
            return
        rate = accepted / proposed
        self._ema[slot] = (
            self.EMA_DECAY * self._ema[slot] + (1.0 - self.EMA_DECAY) * rate
        )
        if accepted >= proposed:
            self._cur_k[slot] = min(self.max_k, self._cur_k[slot] + 1)
        elif accepted == 0:
            self._cur_k[slot] = max(1, self._cur_k[slot] // 2)

    def current_k(self, slot: int) -> int:
        """The slot's adaptive draft budget right now (tests + gauges)."""
        return self._cur_k.get(slot, 0)
