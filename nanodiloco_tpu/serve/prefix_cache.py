"""Shared-prefix KV cache: prefill a common prompt prefix once, reuse it.

The system-prompt pattern (every request opens with the same instruction
block) makes whole-prompt prefill O(requests x prefix) for work that is
O(prefix): K/V at position i depend only on ``tokens[:i+1]`` and the
frozen params, so two prompts with the same token prefix have
bit-identical K/V rows over it (vLLM's PagedAttention observation,
arXiv:2309.06180, on this repo's dense-slot terms).

Granularity is the engine's prefill CHUNK: an entry is one whole chunk
of K/V rows ``[L, chunk_tokens, Hkv, hd]`` keyed by the token tuple of
the ENTIRE prefix through that chunk (a Python dict over token tuples IS
a content-hashed map, with collision resolution for free — no rolling
hash to get wrong). Corollary: a shared prefix shorter than one chunk
never caches, and sharing stops at the last whole-chunk boundary inside
the common prefix — size the chunk at or below the system prompt. Chunk entries chain: a request's lookup walks its
prompt chunk by chunk and stops at the first miss, so a prompt matching
2 of 3 cached chunks still reuses 2. A hit is capped at
``floor((P-1)/chunk)`` chunks — at least the prompt's last token must
prefill for real, because its logits seed the first sampled token.

Admission is explicit and observable: ``insert`` is called by the engine
once a request's prefill COMPLETES (never for requests that opted out),
capacity is bounded in cached tokens with LRU eviction, and every
hit/miss/insert/eviction increments a counter surfaced on the serve
``/metrics``. Single-threaded by design: only the engine's tick thread
calls ``match``/``insert``; ``stats`` reads plain ints and is safe from
the HTTP threads.
"""

from __future__ import annotations

import collections


class PrefixCache:
    """Chunk-granular LRU over token-prefix keys. ``blocks`` values are
    opaque to this class (the engine stores ``(k, v)`` device arrays),
    so every policy decision is testable without a model."""

    def __init__(self, capacity_tokens: int, chunk_tokens: int,
                 on_evict=None) -> None:
        if chunk_tokens < 1:
            raise ValueError(f"chunk_tokens must be >= 1; got {chunk_tokens}")
        if capacity_tokens < chunk_tokens:
            raise ValueError(
                f"capacity_tokens ({capacity_tokens}) must hold at least "
                f"one chunk ({chunk_tokens} tokens)"
            )
        self.chunk_tokens = int(chunk_tokens)
        self.capacity_tokens = int(capacity_tokens)
        # eviction hook, called with the evicted block value: the paged
        # engine derefs the chunk's KV blocks here (dense mode needs
        # nothing — dropping the device arrays frees them)
        self.on_evict = on_evict
        # prefix token tuple (whole chunks) -> block; move_to_end = LRU
        self._blocks: collections.OrderedDict[tuple, object] = (
            collections.OrderedDict()
        )
        self.hits = 0            # lookups that reused >= 1 chunk
        self.misses = 0          # lookups that reused none
        self.hit_tokens = 0      # prompt tokens NOT re-prefilled
        self.insertions = 0      # chunks inserted
        self.evictions = 0       # chunks LRU-evicted
        # weight-generation tag: bumped by ``clear()`` (a serve-weight
        # hot swap invalidates every entry — cached K/V was computed
        # under the OLD params, and a post-swap hit would splice
        # old-weight rows into a new-weight stream, breaking the
        # bit-parity contract). The tag lets tests and gauges pin that
        # a post-swap lookup can never see pre-swap KV.
        self.generation = 0
        self.invalidations = 0   # chunks dropped by clear()

    @property
    def cached_tokens(self) -> int:
        return len(self._blocks) * self.chunk_tokens

    def match(self, prompt, record: bool = True) -> list:
        """Longest chain of cached whole-chunk prefixes of ``prompt``
        (capped so at least one prompt token is left to prefill).
        Returns the blocks in chunk order ([] = miss); bumps LRU on
        every chunk of the hit path. ``record=False`` is a pure PEEK —
        no counters, no LRU movement — for admission paths that must
        size an allocation BEFORE committing to the hit (a rolled-back
        admission must not look like cache traffic)."""
        cs = self.chunk_tokens
        prompt = tuple(prompt)
        max_chunks = (len(prompt) - 1) // cs
        blocks: list = []
        for i in range(max_chunks):
            key = prompt[: (i + 1) * cs]
            block = self._blocks.get(key)
            if block is None:
                break
            if record:
                self._blocks.move_to_end(key)
            blocks.append(block)
        if not record:
            return blocks
        if blocks:
            self.hits += 1
            self.hit_tokens += len(blocks) * cs
        else:
            self.misses += 1
        return blocks

    def insert(self, prompt, n_chunks: int, extract) -> int:
        """Cache the first ``n_chunks`` whole chunks of ``prompt``.
        ``extract(chunk_index)`` materializes the block for a chunk not
        yet cached (the engine copies it off the slot's K/V rows — paid
        only for genuinely new chunks). Returns how many chunks were
        newly inserted; evicts LRU entries past ``capacity_tokens``."""
        cs = self.chunk_tokens
        prompt = tuple(prompt)
        inserted = 0
        for i in range(n_chunks):
            if (i + 1) * cs > self.capacity_tokens:
                # a chain longer than the whole cache can never be
                # looked up intact; inserting its tail would only evict
                # useful entries to store unreachable ones
                break
            key = prompt[: (i + 1) * cs]
            if key in self._blocks:
                self._blocks.move_to_end(key)
                continue
            self._blocks[key] = extract(i)
            self.insertions += 1
            inserted += 1
            while self.cached_tokens > self.capacity_tokens:
                # LRU. A mid-chain eviction strands its longer suffixes
                # (lookup walks from chunk 0 and stops at the gap) until
                # LRU drains them too — bounded staleness, zero extra
                # bookkeeping, and never a wrong hit.
                _key, evicted = self._blocks.popitem(last=False)
                self.evictions += 1
                if self.on_evict is not None:
                    self.on_evict(evicted)
        return inserted

    def clear(self) -> int:
        """Invalidate EVERY cached chunk and bump ``generation`` — the
        weight hot-swap path (``InferenceEngine.swap_weights``): cached
        K/V rows were computed under the old params and are garbage
        under the new ones, so reuse across a swap would break the
        streams-bit-identical-to-solo-``generate()`` contract in the
        quietest possible way (a plausible-looking stream computed from
        stale keys). Runs ``on_evict`` per entry, so the paged engine's
        block references are released exactly as LRU eviction would.
        Returns the number of chunks dropped."""
        n = len(self._blocks)
        while self._blocks:
            _key, evicted = self._blocks.popitem(last=False)
            if self.on_evict is not None:
                self.on_evict(evicted)
        self.invalidations += n
        self.generation += 1
        return n

    def evict_lru(self) -> bool:
        """Evict exactly the LRU entry (False when empty) — the paged
        engine's reclaim-under-pressure path: cached blocks are a
        best-effort optimization, and admission starving behind them
        would be a livelock (the only other eviction trigger is
        ``insert``, which needs a prefill to COMPLETE first)."""
        if not self._blocks:
            return False
        _key, evicted = self._blocks.popitem(last=False)
        self.evictions += 1
        if self.on_evict is not None:
            self.on_evict(evicted)
        return True

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_tokens": self.hit_tokens,
            "insertions": self.insertions,
            "evictions": self.evictions,
            "generation": self.generation,
            "invalidations": self.invalidations,
            "cached_tokens": self.cached_tokens,
            "capacity_tokens": self.capacity_tokens,
            "chunk_tokens": self.chunk_tokens,
        }
