"""Continuous-batching inference serving over the static-shape KV cache.

Layers (each usable alone):
- ``engine.InferenceEngine`` — slot-based decode engine: B cache slots,
  per-request prefill into a free slot, one compiled step advancing all
  live slots per tick.
- ``scheduler.Scheduler`` — FIFO admission queue with backpressure,
  slot allocation, deadlines; deterministic and model-free (any object
  with the engine's prefill/step/release surface works).
- ``server.ServeServer`` — stdlib HTTP daemon: ``POST /v1/generate``,
  ``GET /healthz``, ``GET /metrics`` (OpenMetrics serve gauges).
"""

from nanodiloco_tpu.serve.client import http_get, http_post_json
from nanodiloco_tpu.serve.engine import InferenceEngine
from nanodiloco_tpu.serve.scheduler import (
    GenRequest,
    QueueFull,
    Scheduler,
    Ticket,
)
from nanodiloco_tpu.serve.server import ServeServer

__all__ = [
    "InferenceEngine",
    "http_get",
    "http_post_json",
    "GenRequest",
    "QueueFull",
    "Scheduler",
    "Ticket",
    "ServeServer",
]
