"""Continuous-batching inference serving over the static-shape KV cache.

Layers (each usable alone):
- ``engine.InferenceEngine`` — slot-based decode engine: B cache slots,
  chunked per-request prefill into a free slot (bucketed chunk
  programs, bounded compile count), shared-prefix KV reuse, one
  compiled step advancing all live slots per tick.
- ``prefix_cache.PrefixCache`` — chunk-granular content-keyed LRU over
  prompt-prefix K/V (the system-prompt case prefills once).
- ``speculation.PromptLookupProposer`` — host-side prompt-lookup draft
  proposer for speculative decoding (``spec_k``): n-gram drafts from
  the request's own prompt+output, verified by one batched forward
  per tick; greedy and sampled streams stay bit-identical to solo
  ``generate()`` (exact acceptance).
- ``scheduler.Scheduler`` — SLO-aware admission (priority classes, EDF
  within a class, starvation bound) with backpressure, slot
  allocation, deadlines, and one-prefill-chunk-per-tick interleaving;
  deterministic and model-free (any object with the engine's
  start_prefill/prefill_step/step/release surface works).
- ``server.ServeServer`` — stdlib HTTP daemon: ``POST /v1/generate``,
  ``GET /healthz``, ``GET /metrics`` (OpenMetrics serve gauges).
- ``kvship`` — KV block shipping wire format for disaggregated
  prefill/decode serving (fleet/disagg.py): a parked prefilled
  stream's cache rows + resume cursor travel layout-invariantly
  between replicas via ``/admin/kv/export`` / ``/admin/kv/import``.
"""

from nanodiloco_tpu.serve.block_pool import BlockPool, BlocksExhausted
from nanodiloco_tpu.serve.client import http_get, http_post_json
from nanodiloco_tpu.serve.engine import InferenceEngine
from nanodiloco_tpu.serve.kvship import (
    ShipFormatError,
    ShipMismatchError,
    ShippedKV,
)
from nanodiloco_tpu.serve.prefix_cache import PrefixCache
from nanodiloco_tpu.serve.scheduler import (
    ControlHandle,
    GenRequest,
    QueueFull,
    Scheduler,
    Ticket,
)
from nanodiloco_tpu.serve.server import ServeServer
from nanodiloco_tpu.serve.speculation import PromptLookupProposer

__all__ = [
    "PromptLookupProposer",
    "BlockPool",
    "BlocksExhausted",
    "ControlHandle",
    "InferenceEngine",
    "http_get",
    "http_post_json",
    "GenRequest",
    "PrefixCache",
    "QueueFull",
    "Scheduler",
    "ShipFormatError",
    "ShipMismatchError",
    "ShippedKV",
    "Ticket",
    "ServeServer",
]
