"""Slot-based continuous-batching engine over the static-shape KV cache.

Orca-style (Yu et al., OSDI'22) iteration-level scheduling on TPU terms:
the engine owns ONE preallocated cache whose rows are independent
request slots. Two storage modes share every scheduling surface:

- DENSE (``kv_block_size=0``): one ``[L, B, S_max, Hkv, hd]`` row per
  slot — a 30-token request reserves worst-case ``S_max`` HBM, so slot
  count caps concurrency.
- PAGED (``kv_block_size>0``): one ``[L, num_blocks, block_size, Hkv,
  hd]`` arena plus a per-slot block table (vLLM's PagedAttention
  insight, arXiv:2309.06180, on this repo's static-shape terms). A
  request is admitted with exactly ``ceil((prompt + max_new) /
  block_size)`` blocks — HBM caps concurrency by tokens RESIDENT, not
  slots x worst-case — and its blocks return to the free list the
  moment it retires, expires, or cancels. ``kv_dtype="int8"`` stores
  the arena quantized (per-row scales, quantize on write, dequantize
  in the attention read) for ~4x slots per HBM byte vs float32; the fp
  arena stays bit-identical to solo ``generate()``.

A request's life:

- ``start_prefill(slot, request)`` stages the request into a free slot
  and, when the prefix cache holds the prompt's leading chunks, reuses
  them. In dense mode that copies cached K/V rows in; in paged mode it
  maps the cached chunks' BLOCKS into the slot's table copy-on-write
  (refcount bump, zero device copies) — "copy" never happens, because
  a slot only ever writes past its prefix-hit boundary, into blocks it
  owns exclusively. Paged admission is all-or-nothing: if the pool
  cannot supply the blocks, ``BlocksExhausted`` is raised with nothing
  allocated and nothing counted, and the scheduler leaves the request
  queued (admission gates on free BLOCKS, not just free slots).
- ``prefill_step(slot)`` runs ONE prefill chunk (Sarathi-Serve,
  arXiv:2403.02310: chunked prefill is what keeps a 4k-token prompt
  from freezing every live decode stream between two ticks). The final
  chunk returns the first token; earlier chunks return None. Chunk
  lengths are bucketed to powers of two, so mixed-length traffic
  compiles a BOUNDED program set — not one prefill executable per
  prompt length. Sampling is FUSED into the chunk program: a final
  chunk is one dispatch doing attention+sampling, never
  attention-then-sample.
- every ``step()`` advances ALL decoding slots with a single compiled
  program and returns per-slot token VECTORS (per-slot positions, PRNG
  keys, and sampling params ride as traced arrays; sampling fused into
  the same executable) — admitting a new request or retiring a
  finished one never recompiles and never stops the other streams.
  Without speculation every live slot emits exactly one token; with
  ``spec_k > 0`` host-proposed prompt-lookup drafts
  (serve/speculation.py) are verified by one forward over k+1
  positions per slot and each slot emits its longest accepted prefix
  plus the verified bonus token — 1..k+1 tokens, never zero. Exact
  acceptance (accept a draft iff it equals the token the plain tick
  would have sampled with the same per-step key) keeps EVERY stream —
  greedy and sampled — bit-identical to solo ``generate()``; for the
  deterministic prompt-lookup proposal this rule coincides with
  rejection sampling, so it costs no acceptance either.
- ``release(slot)`` frees the row (mid-prefill or mid-decode). Nothing
  is zeroed: a retired slot's stale K/V is causally unreachable to the
  next occupant. In paged mode every block the slot referenced is
  deref'd — shared prefix blocks survive while the prefix cache (or
  another slot) still holds them; exclusive blocks return to the free
  list immediately.

Chunking math (why it is exact): K/V at position i depend only on
``tokens[:i+1]``, so writing them chunk-by-chunk produces the same cache
bits as one whole-prompt call; each chunk's queries attend causally over
everything already written, which is the same reduction the one-shot
prefill performs row by row. Every chunk starts at the prompt cursor
(``done``) — a multiple of chunk_size, hence block-aligned — and the
final chunk right-pads up to its power-of-two bucket, passing the last
REAL index into the program: pad K/V land past the prompt, causally
unreachable, then overwritten by decode. (Right-padding, never
re-feeding earlier tokens, is what makes copy-on-write safe: a slot
never writes at positions below its prefix-hit boundary, so shared
blocks are read-only by construction.)

Determinism contract (tested, dense AND paged-fp): a request's token
stream is exactly the stream ``generate()`` produces alone with the
same seed and sampling params — through chunked admission AND through a
prefix-cache hit. The per-request PRNG schedule is replicated on the
host at admission — ``key, k0 = split(key(seed))`` for the first token,
then ``split(key, max_new_tokens - 1)`` for the decode steps (the full
array is materialized up front because ``split(key, n)[i]`` depends on
``n`` on this jax) — and each tick feeds every slot its own next key.
The int8 arena trades that bit-parity for HBM: its contract is logit
tolerance + greedy-token parity (tests/test_kv_paging.py), not bits.

Tensor parallelism (``tp > 1``): params shard by the training
``param_specs`` rules, both cache modes shard on the KV-HEAD axis
(``parallel/sharding.py::kv_cache_spec``), and every compiled program
above runs sharded with the final logits replicated before sampling —
the per-step PRNG schedule is unchanged, so a TP stream is bit-identical
to solo ``generate(mesh=...)`` on the same layout. Everything host-side
(block table, free list, refcounts, the prefix cache's chunk registry)
stays UNsharded: a block id names the same physical block on every
shard, so allocation, copy-on-write sharing, and rejection rollback are
degree-independent by construction.

Hot-swap weight deployment (``swap_weights``, fleet/): new params from
the latest training checkpoint replace the serving params atomically —
the KV arenas, block pool, and slot state are untouched (only params
change). Slots tag the weight GENERATION they were admitted under: a
stream in flight at the swap keeps dispatching its own generation's
params (one extra masked dispatch per tick during the transition
window) and finishes bit-identical to solo ``generate()`` on the OLD
weights, while every post-swap admission runs — bit-identically — on
the new ones. The prefix cache is invalidated at the swap: its K/V was
computed under the old params. No recompile: the programs are keyed by
config/shape, and a swap changes neither.

Known divergence, inherited from ``generate`` and narrowed here: dense-
dispatch token-choice MoE sizes expert capacity from the tokens in the
call, so a decode tick routes over B slots where ``generate`` routes
over 1, and a prefill chunk routes over its chunk where ``generate``
routes over the whole prompt. With ample capacity (or
``moe_dispatch="ragged"``) routing is per-token independent and
identical; dead slots are masked out of routing entirely (``active``).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from nanodiloco_tpu.models.config import LlamaConfig
from nanodiloco_tpu.models.generate import (
    decode_slots_fn,
    decode_slots_paged_fn,
    extract_chunk_fn,
    init_kv_cache,
    init_kv_pool,
    insert_chunk_fn,
    kv_bytes_per_token,
    prefill_chunk_fn,
    prefill_chunk_paged_fn,
    verify_slots_fn,
    verify_slots_paged_fn,
)
from nanodiloco_tpu.obs.devtime import DispatchAccountant
from nanodiloco_tpu.obs.telemetry import Histogram
from nanodiloco_tpu.serve import kvship
from nanodiloco_tpu.serve.block_pool import BlockPool, BlocksExhausted
from nanodiloco_tpu.serve.prefix_cache import PrefixCache
from nanodiloco_tpu.serve.speculation import PromptLookupProposer

__all__ = ["InferenceEngine", "BlocksExhausted"]

# blocks-held-per-request histogram bounds (requests, not seconds —
# powers of two up to a long request's worst case)
_BLOCK_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

# emitted-tokens-per-tick histogram bounds (tokens; a spec tick emits
# 1..spec_k+1 per slot)
_SPEC_BUCKETS = (1, 2, 3, 4, 6, 8, 12, 16)


def _floor_pow2(n: int) -> int:
    return 1 << (int(n).bit_length() - 1)


def _ceil_pow2(n: int) -> int:
    return 1 << (int(n) - 1).bit_length() if n > 1 else 1


@dataclasses.dataclass
class _Prefill:
    """One slot's in-flight prefill: the staged request plus the cursor
    into its prompt. ``done`` tokens are already in the slot's cache
    (prefix-cache hit + completed chunks); the chunks-remaining count
    lives in the scheduler's ``_Prefilling``, fed by ``start_prefill``'s
    return value."""

    request: object
    ids: list[int]
    done: int            # prompt tokens whose K/V are written


class InferenceEngine:
    """The slot backend the scheduler drives. Not thread-safe: all calls
    must come from one thread (the scheduler's tick loop)."""

    def __init__(
        self,
        params,
        cfg: LlamaConfig,
        *,
        num_slots: int = 4,
        max_len: int = 1024,
        chunk_size: int = 64,
        prefix_cache_tokens: int = 0,
        kv_block_size: int = 0,
        kv_dtype: str | None = None,
        kv_pool_blocks: int | None = None,
        spec_k: int = 0,
        spec_ngram: int = 3,
        tp: int = 1,
    ) -> None:
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1; got {num_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2; got {max_len}")
        if chunk_size < 1:
            raise ValueError(f"chunk_size must be >= 1; got {chunk_size}")
        if cfg.num_experts and cfg.router_type == "experts_choose":
            raise ValueError(
                "expert-choice routing is training-only (see generate()); "
                "use router_type='tokens_choose' for serving"
            )
        if kv_dtype not in (None, "model", "int8"):
            raise ValueError(
                f"kv_dtype must be 'model' or 'int8'; got {kv_dtype!r}"
            )
        self.kv_dtype = None if kv_dtype == "model" else kv_dtype
        if self.kv_dtype == "int8" and not kv_block_size:
            raise ValueError(
                "int8 KV storage requires the paged cache; pass "
                "kv_block_size > 0"
            )
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0; got {spec_k}")
        # tensor parallelism: shard params (param_specs), the compiled
        # serve programs, and the KV arenas (kv_cache_spec: the KV-head
        # axis) over a tp-axis mesh. Validated LOUDLY here, at boot —
        # a bad degree must be a readable config error, never a shape
        # error out of the first traced program.
        tp = int(tp)
        if tp < 1:
            raise ValueError(f"tp must be >= 1; got {tp}")
        if tp > 1:
            ndev = len(jax.devices())
            if tp > ndev:
                raise ValueError(
                    f"tp={tp} exceeds the {ndev} available "
                    f"device{'s' if ndev != 1 else ''} — the mesh cannot "
                    "be built (use --force-cpu-devices N for virtual "
                    "CPU shards)"
                )
            if cfg.kv_heads % tp:
                raise ValueError(
                    f"tp={tp} does not divide the model's KV-head count "
                    f"({cfg.kv_heads}): the KV arenas shard on the "
                    "KV-head axis, so the degree must divide it evenly"
                )
        self.tp = tp
        if tp > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            from nanodiloco_tpu.parallel.mesh import MeshConfig, build_mesh
            from nanodiloco_tpu.parallel.sharding import named, param_specs

            self.mesh = build_mesh(
                MeshConfig(tp=tp), devices=jax.devices()[:tp]
            )
            self._replicated = NamedSharding(self.mesh, PartitionSpec())
            # params resident in their serving layout up front: the
            # first tick must never pay a resharding transfer
            params = jax.device_put(params, named(self.mesh, param_specs(cfg)))
        else:
            self.mesh = None
            self._replicated = None
        self.params = params
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        # chunk lengths are bucketed to powers of two; capping the top
        # bucket at the largest power of two <= max_len keeps every
        # bucketed write inside the slot row (a bucket can right-pad a
        # final chunk, and dynamic_update_slice would CLAMP an
        # out-of-range write backwards over real positions)
        self.chunk_size = _floor_pow2(min(int(chunk_size), self.max_len))
        self.vocab_size = cfg.vocab_size
        self.paged = bool(kv_block_size)
        self._chunk = None
        self._decode = None
        self._extract = None
        self._insert = None
        b = self.num_slots
        if self.paged:
            # block size: a power of two no larger than the chunk size,
            # so every chunk start (a multiple of chunk_size) is
            # block-aligned and shared prefix chunks map to whole blocks
            self.kv_block_size = _floor_pow2(
                min(int(kv_block_size), self.chunk_size)
            )
            bs = self.kv_block_size
            self.max_blocks = -(-self.max_len // bs)   # allocation bound
            # the TABLE is one chunk of sentinel entries wider than any
            # allocation: a right-padded final bucket then always fits
            # the gathered view (done + bucket <= ceil(max_len/cs)*cs <
            # view), so the paged path NEVER takes the re-feed fallback
            # — which would rewrite rows below the prefix-hit boundary,
            # and in int8 mode re-feed bits are NOT identical (the
            # original chunk attended its own rows as fresh fp; a
            # re-feed reads them dequantized), i.e. it would corrupt
            # shared copy-on-write blocks. Pad writes land on the
            # sentinel and drop; pad reads are causally masked.
            self.table_blocks = self.max_blocks + self.chunk_size // bs
            default_blocks = self.num_slots * self.max_blocks
            nb = int(kv_pool_blocks) if kv_pool_blocks else default_blocks
            # a pool SMALLER than one max_len request is legal — it
            # serves short requests and validate() rejects the long
            # ones outright (they could never be admitted)
            self.block_pool = BlockPool(nb, bs)
            self.pool = self._shard_kv(init_kv_pool(cfg, nb, bs, self.kv_dtype))
            self.cache = None
            self._chunk_paged = prefill_chunk_paged_fn(
                cfg, self.kv_dtype, self.mesh
            )
            self._decode_paged = decode_slots_paged_fn(
                cfg, self.kv_dtype, self.mesh
            )
            # per-slot block tables; the sentinel nb is out of range:
            # reads clamp to causally-dead garbage, writes drop
            self._tables = np.full((b, self.table_blocks), nb, np.int32)
            self._slot_blocks: list[list[int]] = [[] for _ in range(b)]
            self.kv_block_evictions = 0
            self.hist_blocks_per_request = Histogram(_BLOCK_BUCKETS)
        else:
            self.kv_block_size = 0
            self.block_pool = None
            self.pool = None
            self.cache = self._shard_kv(
                init_kv_cache(cfg, self.num_slots, self.max_len)
            )
            self._chunk = prefill_chunk_fn(cfg, self.mesh)
            self._decode = decode_slots_fn(cfg, self.mesh)
            self._extract = extract_chunk_fn(cfg)
            self._insert = insert_chunk_fn(cfg)
        self.prefix_cache = (
            PrefixCache(
                int(prefix_cache_tokens), self.chunk_size,
                on_evict=self._evict_prefix_blocks if self.paged else None,
            )
            if prefix_cache_tokens else None
        )
        # speculative decoding (spec_k > 0): host-side prompt-lookup
        # drafts (serve/speculation.py) verified by ONE compiled forward
        # over k+1 positions per slot. Draft widths bucket to powers of
        # two, so the verify program set is bounded like the chunk set;
        # a tick with no drafts anywhere falls back to the plain decode
        # program, so adversarial traffic pays only the (host) lookup.
        self.spec_k = int(spec_k)
        self.spec_ngram = int(spec_ngram)
        if self.spec_k:
            self.speculator = PromptLookupProposer(
                self.spec_k, max_ngram=self.spec_ngram
            )
            self._verify = (
                verify_slots_paged_fn(cfg, self.kv_dtype, self.mesh)
                if self.paged else verify_slots_fn(cfg, self.mesh)
            )
        else:
            self.speculator = None
            self._verify = None
        self._spec_ok = [False] * self.num_slots   # per-slot opt-in state
        self.spec_draft_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_rejected_tokens = 0
        self.spec_ticks = 0                        # ticks that ran verify
        self.decode_ticks = 0                      # every decode tick
        self.hist_spec_tokens_per_tick = Histogram(_SPEC_BUCKETS)

        # hot-swap weight generations (fleet/): ``swap_weights`` bumps
        # ``deploy_generation`` and stages the new params; slots tag the
        # generation they were ADMITTED under, so during a transition
        # window live streams keep decoding on the weights they started
        # with while new admissions take the new ones — a swap never
        # drops (or silently reweights) an in-flight request.
        self.deploy_generation = 0
        self._params_by_gen: dict[int, object] = {0: self.params}
        self._slot_gen = [0] * b

        s = self.max_len
        self._tokens = np.zeros(b, np.int32)       # next input token per slot
        self._pos = np.zeros(b, np.int32)          # next cache write position
        self._key_valid = np.zeros((b, s), np.int32)
        self._active = np.zeros(b, np.int32)
        self._temp = np.zeros(b, np.float32)
        self._topk = np.zeros(b, np.int32)
        self._topp = np.ones(b, np.float32)
        # per-slot precomputed decode key data [max_new-1, 2] uint32
        self._keys: list[np.ndarray | None] = [None] * b
        self._step_idx = [0] * b
        self._prefills: list[_Prefill | None] = [None] * b
        self._dummy_key = np.asarray(
            jax.random.key_data(jax.random.key(0)), np.uint32
        )
        # debug probe, OFF by default: when ``capture_prefill_logits``
        # is set, each final chunk's logits land here as numpy — the
        # int8 tolerance tests read it. Left off, nothing is copied:
        # a [1, V] device-to-host transfer per admission is real TTFT
        # at production vocab sizes
        self.capture_prefill_logits = False
        self.last_prefill_logits: np.ndarray | None = None
        # device-resident copies of the slot state that only changes at
        # admit/release (key_valid alone is [B, S_max] — re-uploading it
        # every tick would put an H2D transfer on the per-token path)
        self._dev: dict | None = None
        # (kind -> bucket set) of every program shape dispatched, for
        # the layout-qualified compile-count introspection
        self._buckets: dict[str, set[int]] = {}
        # device-time ledger: every dispatch below runs inside one of
        # its fence-timed sections, keyed by the same (kind, bucket,
        # layout) triples as the compile counts (obs/devtime)
        self.accountant = DispatchAccountant()
        # KV block shipping meters (serve/kvship.py): payload bytes,
        # blocks, and wall seconds per direction — the disaggregated
        # fleet's handoff cost counters, surfaced via kvship_stats()
        self.kvship_counts = {
            "export_requests": 0, "import_requests": 0,
            "export_bytes": 0, "import_bytes": 0,
            "export_blocks": 0, "import_blocks": 0,
            "export_seconds": 0.0, "import_seconds": 0.0,
        }

    # -- tensor-parallel plumbing -------------------------------------------

    def _shard_kv(self, kv: dict) -> dict:
        """Commit a KV arena to its serving sharding — the same
        ``kv_arena_leaf_spec`` rule the compiled programs constrain to,
        so the committed layout can never drift from the traced one.
        No-op without a mesh."""
        if self.mesh is None:
            return kv
        from jax.sharding import NamedSharding

        from nanodiloco_tpu.parallel.sharding import kv_arena_leaf_spec

        return {
            name: jax.device_put(
                arr, NamedSharding(self.mesh, kv_arena_leaf_spec(arr.ndim))
            )
            for name, arr in kv.items()
        }

    def _jarr(self, value, dtype=None):
        """Host value -> device array. With a mesh, commit it REPLICATED
        over the tp shards so every program input has an unambiguous
        placement (mixing mesh-committed params with single-device tick
        inputs would make the dispatch placement implementation-defined)."""
        arr = np.asarray(value, dtype) if dtype is not None else np.asarray(value)
        if self.mesh is None:
            return jnp.asarray(arr)
        return jax.device_put(arr, self._replicated)

    # -- hot-swap weight deployment (fleet/) ---------------------------------

    def swap_weights(self, params) -> int:
        """Atomically deploy new params without dropping in-flight
        requests. The new tree must match the serving params leaf for
        leaf (same structure, shapes, dtypes — validated LOUDLY here, at
        the swap, never as a shape error out of the next tick); with a
        mesh it is ``device_put`` into the SAME serving layout boot
        established, so the first post-swap tick never pays a resharding
        transfer. The KV arenas are untouched — only params change — so
        live slots keep their cache rows and finish on the weights they
        were admitted under (their generation's params stay resident
        until the last such slot retires), while every later admission
        runs on the new weights. The prefix cache is INVALIDATED: its
        K/V was computed under the old params, and a post-swap hit
        would splice stale rows into a new-weight stream. Must be
        called from the tick thread (``Scheduler.call_on_tick`` hands a
        swap over from HTTP threads). Returns the new generation."""
        with self.accountant.section("swap", 0, self.kv_layout,
                                     first_is_compile=False):
            return self._swap_weights_inner(params)

    def _swap_weights_inner(self, params) -> int:
        old = jax.tree_util.tree_flatten_with_path(self.params)[0]
        new = jax.tree_util.tree_flatten_with_path(params)[0]
        if [p for p, _ in old] != [p for p, _ in new]:
            raise ValueError(
                "swap_weights: new params tree structure does not match "
                "the serving params (different architecture?)"
            )
        for (path, a), (_, b) in zip(old, new):
            if tuple(a.shape) != tuple(b.shape) or a.dtype != b.dtype:
                name = "/".join(str(getattr(k, "key", k)) for k in path)
                raise ValueError(
                    f"swap_weights: leaf {name} is "
                    f"{tuple(b.shape)}:{b.dtype} but the serving engine "
                    f"holds {tuple(a.shape)}:{a.dtype} — the checkpoint "
                    "does not fit this engine's compiled programs"
                )
        if self.mesh is not None:
            from nanodiloco_tpu.parallel.sharding import named, param_specs

            params = jax.device_put(
                params, named(self.mesh, param_specs(self.cfg))
            )
        # fence the transfer: the swap section's seconds must cover the
        # H2D upload, not just its dispatch
        jax.block_until_ready(params)
        self.deploy_generation += 1
        self._params_by_gen[self.deploy_generation] = params
        self.params = params
        if self.prefix_cache is not None:
            # cached K/V was computed under the old weights; reusing it
            # would break the bit-parity contract (paged mode derefs the
            # cached blocks through on_evict, exactly like LRU eviction)
            self.prefix_cache.clear()
        self._prune_param_generations()
        return self.deploy_generation

    def _prune_param_generations(self) -> None:
        """Drop param generations no live (or mid-prefill) slot
        references — an old snapshot stays resident only while a stream
        admitted under it is still running."""
        live = {self.deploy_generation}
        for s in range(self.num_slots):
            if self._active[s] or self._prefills[s] is not None:
                live.add(self._slot_gen[s])
        for g in [g for g in self._params_by_gen if g not in live]:
            del self._params_by_gen[g]

    def _gen_groups(self) -> dict[int, list[int]]:
        """Live slots grouped by the weight generation they were
        admitted under (one group in the steady state)."""
        groups: dict[int, list[int]] = {}
        for s in range(self.num_slots):
            if self._active[s]:
                groups.setdefault(self._slot_gen[s], []).append(s)
        return groups

    # -- request validation (shared with the server's 400 path) -------------

    def blocks_for(self, prompt_tokens: int, max_new_tokens: int) -> int:
        """KV blocks a request occupies for its whole life (paged mode):
        prompt + completion rows, rounded up to whole blocks. Allocation
        is up-front and exact, so a request admitted never runs out of
        cache mid-decode."""
        return -(-(prompt_tokens + max_new_tokens) // self.kv_block_size)

    def validate(self, prompt, max_new_tokens: int) -> None:
        """Raises ValueError when a request cannot be served by this
        engine's static shapes (including a paged pool it could NEVER
        fit — transient block shortage is ``BlocksExhausted`` at
        admission instead, and retryable)."""
        if len(prompt) < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1; got {max_new_tokens}"
            )
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)} tokens) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine's max_len "
                f"({self.max_len})"
            )
        if self.paged:
            need = self.blocks_for(len(prompt), max_new_tokens)
            if need > self.block_pool.num_blocks:
                raise ValueError(
                    f"request needs {need} KV blocks but the pool only "
                    f"has {self.block_pool.num_blocks} in total — it can "
                    f"never be admitted"
                )
        bad = [t for t in prompt if not 0 <= int(t) < self.vocab_size]
        if bad:
            raise ValueError(
                f"prompt tokens {bad[:4]} outside the model vocabulary "
                f"({self.vocab_size})"
            )

    # -- slot lifecycle ------------------------------------------------------

    def start_prefill(self, slot: int, request) -> int:
        """Stage ``request`` into free slot ``slot``: validate, reuse
        any cached shared-prefix K/V, and return the number of prefill
        chunks still to run (>= 1 — the last prompt token always
        prefills for real, its logits seed the first sample). Paged
        mode allocates the request's whole block budget here,
        all-or-nothing: ``BlocksExhausted`` (nothing mutated, nothing
        counted) tells the scheduler to keep the request queued until
        blocks free up."""
        ids = [int(t) for t in request.prompt]
        self.validate(ids, request.max_new_tokens)
        done = 0
        use_cache = self.prefix_cache is not None and getattr(
            request, "prefix_cache", True
        )
        if self.paged:
            cs, bs = self.chunk_size, self.kv_block_size
            need = self.blocks_for(len(ids), request.max_new_tokens)
            # PEEK the prefix cache first: sizing must precede any
            # side effect so a block-starved admission rolls back to
            # nothing (no counters, no LRU churn, no refs). Under
            # pressure, RECLAIM cache-only blocks by evicting LRU
            # prefixes: cached K/V is a best-effort optimization, and
            # without this path a cache that swallowed the pool would
            # livelock admission forever (no prefill can complete, so
            # insert-side eviction never runs). Each eviction can
            # invalidate the matched chain, so the peek re-walks.
            while True:
                chains = (
                    self.prefix_cache.match(ids, record=False)
                    if use_cache else []
                )
                shared = [blk for chunk in chains for blk in chunk]
                own_need = need - len(shared)
                if own_need <= self.block_pool.free_blocks:
                    break
                if (self.prefix_cache is None
                        or not self.prefix_cache.evict_lru()):
                    raise BlocksExhausted(
                        f"request needs {own_need} KV blocks "
                        f"({need} total, {len(shared)} shared) but only "
                        f"{self.block_pool.free_blocks}/"
                        f"{self.block_pool.num_blocks} are free"
                    )
            # commit: record the hit/miss for real (same chain —
            # nothing mutated between the peek and this), take the
            # references
            if use_cache:
                chains = self.prefix_cache.match(ids)
            own = self.block_pool.alloc(own_need)
            self.block_pool.ref(shared)
            blocks = shared + own
            self._slot_blocks[slot] = blocks
            row = np.full(self.table_blocks, self.block_pool.num_blocks,
                          np.int32)
            row[: len(blocks)] = blocks
            self._tables[slot] = row
            self._dev = None
            done = len(chains) * cs
        elif use_cache:
            blocks = self.prefix_cache.match(ids)
            for i, (k, v) in enumerate(blocks):
                self.cache = self._insert(
                    self.cache, k, v, self._jarr(slot, np.int32),
                    self._jarr(i * self.chunk_size, np.int32),
                )
            done = len(blocks) * self.chunk_size
        # the request is admitted under the CURRENT weights; every chunk
        # and decode tick of its life dispatches this generation's
        # params, even if a hot swap lands mid-stream
        self._slot_gen[slot] = self.deploy_generation
        self._prefills[slot] = _Prefill(request, ids, done)
        return -(-(len(ids) - done) // self.chunk_size)

    def _run_chunk(self, slot: int, chunk, valid, pos: int, last: int,
                   key_data, temp: float, top_k: int, top_p: float):
        """Dispatch one (bucketed) chunk through the mode's compiled
        program; returns (token scalar, logits [1, V])."""
        self._buckets.setdefault("prefill_chunk", set()).add(len(chunk))
        params = self._params_by_gen[self._slot_gen[slot]]
        args = (
            self._jarr([chunk], np.int32), self._jarr(valid),
            self._jarr(pos, np.int32), self._jarr(last, np.int32),
            self._jarr(key_data, np.uint32),
            self._jarr(temp, np.float32), self._jarr(top_k, np.int32),
            self._jarr(top_p, np.float32),
        )
        with self.accountant.section("prefill_chunk", len(chunk),
                                     self.kv_layout):
            if self.paged:
                tok, logits, self.pool = self._chunk_paged(
                    params, self.pool,
                    self._jarr(self._tables[slot]), *args,
                )
            else:
                tok, logits, self.cache = self._chunk(
                    params, self.cache, args[0], args[1],
                    self._jarr(slot, np.int32), *args[2:],
                )
            # fence INSIDE the section: interior chunks have no host
            # consumer (the final chunk's int(tok) is the only natural
            # sync), and an unfenced async dispatch would be timed as
            # free. One output suffices — the chunk is one executable,
            # its buffers materialize together.
            jax.block_until_ready(tok)
        return tok, logits

    def prefill_step(self, slot: int) -> int | None:
        """Run ONE prefill chunk for the staged request in ``slot``.
        Returns None while chunks remain; the final chunk samples (in
        the same executable) and returns the first token, leaving the
        slot live for ``step()``."""
        pf = self._prefills[slot]
        if pf is None:
            raise ValueError(f"slot {slot} has no prefill in flight")
        ids, p = pf.ids, len(pf.ids)
        remaining = p - pf.done
        dummy = (self._dummy_key, 0.0, 0, 1.0)  # interior chunks: unused
        if remaining > self.chunk_size:
            # full interior chunk: exactly chunk_size real tokens
            lo = pf.done
            chunk = ids[lo:lo + self.chunk_size]
            self._run_chunk(
                slot, chunk, np.ones((1, self.chunk_size), np.int32),
                lo, self.chunk_size - 1, *dummy,
            )
            pf.done += self.chunk_size
            return None

        # final chunk, bucketed to a power of two and right-padded: the
        # chunk always starts AT the cursor (never re-feeds earlier
        # positions — which is what makes shared prefix blocks read-only
        # under paging), pads land past the prompt (causally unreachable,
        # then overwritten by decode), and the true last-real index rides
        # into the program as a traced scalar. One exception, DENSE
        # only: when the padded bucket would poke past the cache view
        # (max_len not a multiple of the bucket — dynamic_update_slice
        # would CLAMP the write backwards over real rows), fall back to
        # RE-FEEDING the prompt's last ``bucket`` tokens: recomputed
        # fp K/V bits are identical to what those positions already
        # hold (same tokens, same positions, same params), so the
        # rewrite is a no-op and the write stays in range. The PAGED
        # view is a chunk wider than any allocation precisely so this
        # branch can never trigger there — a paged re-feed would write
        # below the prefix-hit boundary, and in int8 mode those bits
        # are NOT a no-op (shared-block corruption).
        bucket = _ceil_pow2(remaining)
        view = (
            self.table_blocks * self.kv_block_size if self.paged
            else self.max_len
        )
        if pf.done + bucket <= view:
            lo = pf.done
            chunk = ids[lo:] + [0] * (bucket - remaining)
            valid = np.zeros((1, bucket), np.int32)
            valid[0, :remaining] = 1
            last = remaining - 1
        else:  # overflow implies done >= chunk_size >= bucket, so lo >= 0
            lo = p - bucket
            chunk = ids[lo:]
            valid = np.ones((1, bucket), np.int32)
            last = bucket - 1
        req = pf.request
        temp = float(req.temperature)
        top_k = min(int(req.top_k), self.vocab_size)
        top_p = float(req.top_p)
        # the one-shot generate()'s exact key schedule, replayed per slot
        key = jax.random.key(int(req.seed))
        karr = jax.random.split(key)  # karr[0] = rest, karr[1] = k0
        tok, logits = self._run_chunk(
            slot, chunk, valid, lo, last,
            np.asarray(jax.random.key_data(karr[1]), np.uint32),
            temp, top_k, top_p,
        )
        tok0 = int(tok)
        if self.capture_prefill_logits:
            self.last_prefill_logits = np.asarray(logits)
        pf.done = p
        n = int(req.max_new_tokens)
        self._keys[slot] = (
            np.asarray(jax.random.key_data(jax.random.split(karr[0], n - 1)),
                       np.uint32)
            if n > 1 else np.zeros((0, 2), np.uint32)
        )
        self._step_idx[slot] = 0
        self._pos[slot] = p
        self._key_valid[slot] = 1
        self._tokens[slot] = tok0
        self._temp[slot] = temp
        self._topk[slot] = top_k
        self._topp[slot] = top_p
        self._active[slot] = 1
        # speculation is per-request opt-in (``GenRequest.speculate``):
        # the proposer only ever sees opted-in slots
        self._spec_ok[slot] = bool(self.spec_k) and bool(
            getattr(req, "speculate", True)
        )
        if self._spec_ok[slot]:
            self.speculator.begin(slot, ids, tok0)
        self._dev = None  # slot state changed: re-stage on the next step

        self._prefills[slot] = None
        if (
            self.prefix_cache is not None
            and getattr(req, "prefix_cache", True)
            # a slot admitted before a hot swap computed these K/V rows
            # under the OLD weights: inserting them into the (cleared,
            # current-generation) cache would hand stale rows to the
            # next same-prefix request — the exact corruption clear()
            # exists to prevent
            and self._slot_gen[slot] == self.deploy_generation
        ):
            # explicit admission: every completed (non-opted-out)
            # prefill offers its whole-chunk prefix; only chunks not
            # already cached are registered
            cs = self.chunk_size
            n_chunks = (p - 1) // cs
            if self.paged:
                # zero-copy: the cache takes a REFERENCE to the slot's
                # own blocks for each new chunk (bumping their refcount)
                # — the rows never move, and they outlive the slot
                cpb = cs // self.kv_block_size

                def extract(i: int):
                    blks = tuple(
                        int(x) for x in
                        self._tables[slot][i * cpb:(i + 1) * cpb]
                    )
                    self.block_pool.ref(blks)
                    return blks
            else:

                def extract(i: int):
                    k, v = self._extract(
                        self.cache, self._jarr(slot, np.int32),
                        self._jarr(i * cs, np.int32), cs,
                    )
                    return k, v

            self.prefix_cache.insert(ids, n_chunks, extract)
        return tok0

    def prefill(self, slot: int, request) -> int:
        """Whole-prompt convenience: stage and run every chunk in one
        call (the parity tests' sequential driver; the scheduler
        interleaves ``prefill_step`` with decode ticks instead)."""
        self.start_prefill(slot, request)
        while True:
            tok = self.prefill_step(slot)
            if tok is not None:
                return tok

    def _stage_dev(self) -> dict:
        """Device-resident slot state that only changes at admit/release
        (uploading key_valid/tables every tick would put an H2D copy on
        the per-token path)."""
        if self._dev is None:
            self._dev = {
                "temp": self._jarr(self._temp),
                "topk": self._jarr(self._topk),
                "topp": self._jarr(self._topp),
                "active": self._jarr(self._active),
            }
            if self.paged:
                self._dev["tables"] = self._jarr(self._tables)
            else:
                self._dev["key_valid"] = self._jarr(self._key_valid)
        return self._dev

    def _collect_drafts(self) -> tuple[list[list[int]], int]:
        """Ask the proposer for each live opted-in slot's drafts, capped
        so the tick can never emit past the request's key schedule
        (emitted <= draft_len + 1 <= remaining). Returns (per-slot draft
        lists, max draft length this tick)."""
        drafts: list[list[int]] = [[] for _ in range(self.num_slots)]
        k_tick = 0
        new_tick = getattr(self.speculator, "new_tick", None)
        if new_tick is not None:
            new_tick()
        for s in range(self.num_slots):
            if not self._active[s] or not self._spec_ok[s]:
                continue
            ks = self._keys[s]
            # keys has max_new - 1 entries; position j of the verify
            # window consumes key[step_idx + j], so the last legal draft
            # index is len(keys) - step_idx - 1 (the +1 bonus token then
            # lands exactly on the request's final step)
            cap = min(self.spec_k, len(ks) - self._step_idx[s] - 1)
            if cap <= 0:
                continue
            d = list(self.speculator.propose(s, cap))[:cap]
            if d:
                drafts[s] = [int(t) for t in d]
                k_tick = max(k_tick, len(d))
        return drafts, k_tick

    def step(self) -> list[list[int]]:
        """Advance every live slot 1..spec_k+1 tokens (one compiled
        tick, sampling fused in). Returns per-slot emitted-token lists
        (empty for inactive slots). Without speculation — or on a tick
        where no slot has a draft — every live slot emits exactly one
        token via the plain decode program; with drafts in flight, ONE
        verify dispatch covers every slot and each emits its accepted
        prefix plus the verified bonus token (never zero: all-reject
        still makes one token of forward progress)."""
        b = self.num_slots
        drafts, k_tick = (
            self._collect_drafts() if self.spec_k
            else ([[] for _ in range(b)], 0)
        )
        self.decode_ticks += 1
        if k_tick == 0:
            return self._step_plain()
        return self._step_verify(drafts, k_tick)

    def _gen_dispatches(self, dev) -> list[tuple[object, list[int], object]]:
        """(params, slots, active array) per weight generation with a
        live slot. The steady state — every live slot on one generation
        — is ONE dispatch reusing the cached device-resident mask, the
        exact pre-hot-swap behavior. During a swap's transition window
        there is one masked dispatch per generation: each stream runs
        under the weights it was admitted with, and the dispatches
        compose because inactive slots' cache writes are masked/dropped
        (the PR-6 fix) and routing masks dead slots out entirely."""
        groups = self._gen_groups()
        if len(groups) <= 1:
            slots = next(iter(groups.values())) if groups else []
            gen = next(iter(groups)) if groups else self.deploy_generation
            return [(self._params_by_gen[gen], slots, dev["active"])]
        out = []
        for gen in sorted(groups):
            act = np.zeros(self.num_slots, np.int32)
            act[groups[gen]] = 1
            out.append((self._params_by_gen[gen], groups[gen],
                        self._jarr(act)))
        return out

    def _step_plain(self) -> list[list[int]]:
        b = self.num_slots
        keys_now = np.empty((b, 2), np.uint32)
        for s in range(b):
            ks = self._keys[s]
            if self._active[s] and ks is not None and self._step_idx[s] < len(ks):
                keys_now[s] = ks[self._step_idx[s]]
            else:
                keys_now[s] = self._dummy_key
        self._buckets.setdefault("decode", set()).add(1)
        dev = self._stage_dev()
        tokens = self._jarr(self._tokens)
        pos = self._jarr(self._pos)
        keys = self._jarr(keys_now)
        out: list[list[int]] = [[] for _ in range(b)]
        for params, slots, active in self._gen_dispatches(dev):
            with self.accountant.section("decode", 1, self.kv_layout):
                if self.paged:
                    nxt, self.pool = self._decode_paged(
                        params, self.pool, dev["tables"],
                        tokens, pos, keys,
                        dev["temp"], dev["topk"], dev["topp"], active,
                    )
                else:
                    nxt, self.cache = self._decode(
                        params, self.cache,
                        tokens, pos,
                        dev["key_valid"], keys,
                        dev["temp"], dev["topk"], dev["topp"], active,
                    )
                # the host fetch below is the tick's natural fence;
                # inside the section so the measured seconds cover the
                # program, not just its dispatch
                nxt = np.asarray(nxt)
            for s in slots:
                self._pos[s] += 1
                self._step_idx[s] += 1
                self._tokens[s] = nxt[s]
                tok = int(nxt[s])
                if self._spec_ok[s]:
                    self.speculator.observe(s, [tok])
                out[s] = [tok]
        return out

    def _step_verify(self, drafts: list[list[int]], k_tick: int) -> list[list[int]]:
        """One speculative tick: verify up to ``k_tick`` drafts per slot
        (bucketed to a power of two — bounded verify-program set) in a
        single forward over k+1 positions, emit each slot's longest
        accepted prefix + bonus token, and advance cursors by the
        emission count. Rejected positions' K/V rows sit PAST the
        advanced cursor inside the slot's own allocation (or dropped at
        the table sentinel) and are rewritten by a later tick before any
        query can reach them — rollback is cursor arithmetic, with
        nothing to free and nothing leakable."""
        b = self.num_slots
        bucket = min(_ceil_pow2(k_tick), self.spec_k)
        t = bucket + 1
        tokens = np.zeros((b, t), np.int32)
        tokens[:, 0] = self._tokens
        dlen = np.zeros(b, np.int32)
        keys_now = np.empty((b, t, 2), np.uint32)
        keys_now[:] = self._dummy_key
        for s in range(b):
            d = drafts[s][:bucket]
            if d:
                tokens[s, 1:1 + len(d)] = d
                dlen[s] = len(d)
            ks = self._keys[s]
            if self._active[s] and ks is not None:
                lo = self._step_idx[s]
                n = min(t, len(ks) - lo)
                if n > 0:
                    keys_now[s, :n] = ks[lo:lo + n]
        self._buckets.setdefault("verify", set()).add(t)
        dev = self._stage_dev()
        jtokens = self._jarr(tokens)
        jpos = self._jarr(self._pos)
        jdlen = self._jarr(dlen)
        jkeys = self._jarr(keys_now)
        out: list[list[int]] = [[] for _ in range(b)]
        for params, slots, active in self._gen_dispatches(dev):
            with self.accountant.section("verify", t, self.kv_layout):
                if self.paged:
                    sampled, counts, self.pool = self._verify(
                        params, self.pool, dev["tables"],
                        jtokens, jpos, jdlen, jkeys,
                        dev["temp"], dev["topk"], dev["topp"], active,
                    )
                else:
                    sampled, counts, self.cache = self._verify(
                        params, self.cache, jtokens, jpos, jdlen,
                        dev["key_valid"], jkeys,
                        dev["temp"], dev["topk"], dev["topp"], active,
                    )
                sampled = np.asarray(sampled)
                counts = np.asarray(counts)
            for s in slots:
                c = int(counts[s])
                emitted = [int(v) for v in sampled[s, :c]]
                self._pos[s] += c
                self._step_idx[s] += c
                self._tokens[s] = emitted[-1]
                proposed = int(dlen[s])
                accepted = c - 1
                self.spec_draft_tokens += proposed
                self.spec_accepted_tokens += accepted
                self.spec_rejected_tokens += proposed - accepted
                if proposed:
                    # drafting slots only: a no-draft neighbour riding
                    # the verify tick emits 1 by construction, and
                    # counting it would make the gated tokens-per-tick
                    # economics measure batch composition instead of
                    # speculation quality
                    self.hist_spec_tokens_per_tick.observe(c)
                if self._spec_ok[s]:
                    if proposed:
                        self.speculator.feedback(s, proposed, accepted)
                    self.speculator.observe(s, emitted)
                out[s] = emitted
        self.spec_ticks += 1
        return out

    def warm_spec(self) -> int:
        """Compile every verify-program bucket before traffic arrives
        (spec_k buckets the draft width to powers of two; each bucket
        is one executable). Drives a throwaway greedy request through
        slot 0 with a scripted proposer that walks the bucket widths,
        then releases it — nothing observable leaks (no prefix-cache
        insert, blocks returned). Requires an idle engine (call at
        startup, before the tick loop owns the slots). Returns the
        number of buckets warmed; no-op without speculation."""
        if not self.spec_k:
            return 0
        if any(self._active) or any(p is not None for p in self._prefills):
            raise RuntimeError("warm_spec needs an idle engine")
        # widest first: the cap arithmetic (len(keys) - step_idx - 1)
        # shrinks as the throwaway stream advances, so the width that
        # needs the most headroom goes while headroom is maximal
        widths = sorted({
            min(_ceil_pow2(k), self.spec_k)
            for k in range(1, self.spec_k + 1)
        }, reverse=True)

        class _Ramp:
            """Proposes exactly ``self.k`` junk drafts per tick."""

            def __init__(self, vocab: int) -> None:
                self.k = 0
                self.tok = vocab - 1

            def begin(self, *a):
                pass

            def release(self, *a):
                pass

            def propose(self, slot, cap):
                return [self.tok] * min(self.k, cap)

            def observe(self, *a):
                pass

            def feedback(self, *a):
                pass

        from nanodiloco_tpu.serve.scheduler import GenRequest

        prompt_len = min(8, self.max_len // 2)
        req = GenRequest(
            prompt=(1,) * prompt_len,
            max_new_tokens=max(2, min(
                (self.spec_k + 2) * len(widths), self.max_len - prompt_len,
            )),
            prefix_cache=False,
        )
        saved = self.speculator
        ramp = _Ramp(self.vocab_size)
        self.speculator = ramp
        try:
            self.prefill(0, req)
            self._spec_ok[0] = True
            for w in widths:
                ramp.k = w
                self.step()
        finally:
            self.speculator = saved
            self.release(0)
            # the ramp's ticks are warmup, not traffic: /metrics must
            # never report them. Device seconds follow the same rule;
            # COMPILE seconds stay — warmup is exactly when the verify
            # buckets compile, and that budget line is the point
            self.reset_spec_stats()
            self.accountant.reset_device_seconds()
        return len(widths)

    def reset_spec_stats(self) -> None:
        """Zero the speculation counters and histogram — warmup traffic
        (warm_spec's ramp, a bench's compile-warming request) must not
        leak into a measured window or the gauges."""
        self.spec_draft_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_rejected_tokens = 0
        self.spec_ticks = 0
        self.decode_ticks = 0
        self.hist_spec_tokens_per_tick = Histogram(_SPEC_BUCKETS)

    def release(self, slot: int) -> None:
        self._active[slot] = 0
        self._key_valid[slot] = 0
        self._keys[slot] = None
        self._pos[slot] = 0
        self._tokens[slot] = 0
        # reset sampling params too: _sample_slots' batch-level cond
        # fast paths (all-greedy -> argmax only; no top-k/p -> no vocab
        # sorts) test jnp.any over the WHOLE row set, and one retired
        # sampled request's stale temperature would otherwise pin every
        # later all-greedy tick onto the slow branch
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 1.0
        self._prefills[slot] = None
        if self._spec_ok[slot]:
            self.speculator.release(slot)
        self._spec_ok[slot] = False
        if self.paged:
            blocks = self._slot_blocks[slot]
            if blocks:
                self.hist_blocks_per_request.observe(len(blocks))
                self.block_pool.deref(blocks)
            self._slot_blocks[slot] = []
            self._tables[slot] = self.block_pool.num_blocks
        self._dev = None
        # a retiring slot may have been the last reference to a
        # pre-swap weight generation — release the old snapshot
        self._prune_param_generations()

    def _evict_prefix_blocks(self, blocks) -> None:
        """Prefix-cache LRU eviction hook (paged): drop the cache's
        references; blocks still mapped into a live slot survive until
        that slot releases them."""
        self.block_pool.deref(blocks)
        self.kv_block_evictions += len(blocks)

    # -- KV block shipping (serve/kvship.py; fleet/disagg.py) ----------------

    def export_kv(self, slot: int) -> dict:
        """Export ``slot``'s written KV rows for shipping to another
        replica (the disaggregated prefill->decode handoff). Returns the
        layout-invariant raw pieces — ``k``/``v`` as ``[L, pos, Hkv,
        hd]`` host arrays in the ARENA's storage dtype (plus
        ``ks``/``vs`` per-row f32 scales from an int8 arena), the
        fingerprint fields, and the cache cursor ``pos`` — which the
        server packs into the wire doc together with the cursor the
        scheduler owns (emitted tokens, request spec). Only blocks
        actually written travel: a paged export gathers the used blocks
        device-side and transfers those, never the slot's whole
        allocation. Read-only: the slot stays live (release is the
        scheduler's call, after the export is in hand)."""
        if not self._active[slot]:
            raise ValueError(f"slot {slot} has no live stream to export")
        t0 = time.perf_counter()
        pos = int(self._pos[slot])
        blocks_moved = 0
        if self.paged:
            bs = self.kv_block_size
            nb = -(-pos // bs)
            blocks = self._slot_blocks[slot][:nb]
            idx = jnp.asarray(np.asarray(blocks, np.int32))
            k = np.asarray(self.pool["k"][:, idx])
            v = np.asarray(self.pool["v"][:, idx])
            layers = k.shape[0]
            k = k.reshape(layers, nb * bs, *k.shape[3:])[:, :pos]
            v = v.reshape(layers, nb * bs, *v.shape[3:])[:, :pos]
            ks = vs = None
            if self.kv_dtype == "int8":
                ks = np.asarray(self.pool["ks"][:, idx]).reshape(
                    layers, nb * bs)[:, :pos].astype(np.float32)
                vs = np.asarray(self.pool["vs"][:, idx]).reshape(
                    layers, nb * bs)[:, :pos].astype(np.float32)
            blocks_moved = nb
        else:
            k = np.asarray(self.cache["k"][:, slot, :pos])
            v = np.asarray(self.cache["v"][:, slot, :pos])
            ks = vs = None
        out = {
            "config": kvship.config_fingerprint(self.cfg),
            "generation": int(self._slot_gen[slot]),
            "wire_dtype": "int8" if ks is not None else str(k.dtype),
            "pos": pos,
            "k": k, "v": v, "ks": ks, "vs": vs,
        }
        nbytes = k.nbytes + v.nbytes
        if ks is not None:
            nbytes += ks.nbytes + vs.nbytes
        c = self.kvship_counts
        c["export_requests"] += 1
        c["export_bytes"] += int(nbytes)
        c["export_blocks"] += blocks_moved
        c["export_seconds"] += time.perf_counter() - t0
        return out

    def _convert_wire(self, shipped):
        """Wire rows -> this arena's storage form per the kvship dtype
        rules: verbatim when bit-parity is preservable, requantize
        (amax/127) into an int8 arena, dequantize out of an int8 wire;
        an fp wire into a DIFFERENT fp arena dtype is a loud
        ``ShipMismatchError`` — never a silent cast."""
        arena_int8 = self.paged and self.kv_dtype == "int8"
        wire_int8 = shipped.wire_dtype == "int8"
        if arena_int8:
            if wire_int8:
                return shipped.k, shipped.v, shipped.ks, shipped.vs
            qk, sk = kvship.quantize_rows(shipped.k)
            qv, sv = kvship.quantize_rows(shipped.v)
            return qk, qv, sk, sv
        cdt = np.asarray(jnp.zeros((), self.cfg.dtype)).dtype
        if wire_int8:
            return (kvship.dequantize_rows(shipped.k, shipped.ks, cdt),
                    kvship.dequantize_rows(shipped.v, shipped.vs, cdt),
                    None, None)
        if np.dtype(shipped.k.dtype) != cdt:
            raise kvship.ShipMismatchError(
                f"fp wire dtype {shipped.wire_dtype} does not match "
                f"this arena's {cdt} — casting fp bits across dtypes "
                "would silently break the bit-parity contract"
            )
        return shipped.k, shipped.v, None, None

    def _import_paged(self, slot: int, ids, request, pos: int,
                      k, v, ks, vs) -> int:
        """Re-block shipped rows into this engine's pool geometry: the
        request's FULL block budget is allocated all-or-nothing at
        refcount 1 (``BlocksExhausted`` stays the retryable admission
        signal, and ``release`` derefs exactly like a local admission —
        refcount conservation needs no new path), the written rows land
        in the leading blocks, and the trailing blocks hold the
        decode-to-come. Returns the block count the payload filled."""
        bs = self.kv_block_size
        need = self.blocks_for(len(ids), request.max_new_tokens)
        own = self.block_pool.alloc(need)
        try:
            nb = -(-pos // bs)
            layers, heads, hd = k.shape[0], k.shape[2], k.shape[3]

            def blockify(rows):
                pad = np.zeros((layers, nb * bs, heads, hd), rows.dtype)
                pad[:, :pos] = rows
                return pad.reshape(layers, nb, bs, heads, hd)

            idx = jnp.asarray(np.asarray(own[:nb], np.int32))
            self.pool["k"] = self.pool["k"].at[:, idx].set(
                jnp.asarray(blockify(k), self.pool["k"].dtype))
            self.pool["v"] = self.pool["v"].at[:, idx].set(
                jnp.asarray(blockify(v), self.pool["v"].dtype))
            if ks is not None:

                def blockify_s(sc):
                    pad = np.zeros((layers, nb * bs), np.float32)
                    pad[:, :pos] = sc
                    return pad.reshape(layers, nb, bs)

                self.pool["ks"] = self.pool["ks"].at[:, idx].set(
                    jnp.asarray(blockify_s(ks)))
                self.pool["vs"] = self.pool["vs"].at[:, idx].set(
                    jnp.asarray(blockify_s(vs)))
            if self.mesh is not None:
                self.pool = self._shard_kv(self.pool)
            row = np.full(self.table_blocks, self.block_pool.num_blocks,
                          np.int32)
            row[:need] = own
            self._tables[slot] = row
            self._slot_blocks[slot] = own
            return nb
        except BaseException:
            # a failed scatter must not leak the allocation (zero-leak
            # under mid-ship failure is part of the ship contract)
            self.block_pool.deref(own)
            raise

    def import_kv(self, slot: int, request, shipped) -> None:
        """Import a shipped stream into free slot ``slot`` and resume it
        mid-request. Validates the fingerprint first (``ShipMismatch
        Error`` — the server's 409 — on an architecture or weight-
        generation mismatch: shipped rows from other weights would be
        silent garbage), re-blocks the rows into this engine's own pool
        geometry, converts dtypes per the kvship rules, then replicates
        ``prefill_step``'s activation tail exactly: the PRNG schedule is
        rebuilt from the request seed (no key material travels), the
        step cursor from the emitted-token count — so the next decode
        tick is bit-identical to the tick the exporting replica would
        have run. The prefix cache is NOT populated from shipped rows
        (a requantized payload would hand non-parity rows to unrelated
        local requests)."""
        if self._active[slot] or self._prefills[slot] is not None:
            raise ValueError(f"slot {slot} is busy")
        t0 = time.perf_counter()
        fp = kvship.config_fingerprint(self.cfg)
        if shipped.config != fp:
            raise kvship.ShipMismatchError(
                f"config fingerprint {shipped.config} does not match "
                f"this engine ({fp}) — different architecture/config"
            )
        if int(shipped.generation) != self.deploy_generation:
            raise kvship.ShipMismatchError(
                f"weight generation {shipped.generation} does not match "
                f"this replica's deploy generation "
                f"{self.deploy_generation} — resuming across weight "
                "generations would mix caches from different params"
            )
        ids = [int(t) for t in request.prompt]
        self.validate(ids, request.max_new_tokens)
        emitted = [int(t) for t in shipped.emitted]
        if len(ids) != shipped.prompt_len:
            raise kvship.ShipFormatError(
                f"request prompt has {len(ids)} tokens but the payload "
                f"was exported for prompt_len={shipped.prompt_len}"
            )
        if len(emitted) > int(request.max_new_tokens):
            raise kvship.ShipFormatError(
                f"{len(emitted)} emitted tokens exceed the request's "
                f"max_new_tokens={request.max_new_tokens}"
            )
        bad = [t for t in emitted if not 0 <= t < self.vocab_size]
        if bad:
            raise kvship.ShipFormatError(
                f"emitted tokens {bad[:4]} outside the model vocabulary "
                f"({self.vocab_size})"
            )
        pos = int(shipped.pos)
        arena = self.pool["k"] if self.paged else self.cache["k"]
        layers, heads, hd = arena.shape[0], arena.shape[-2], arena.shape[-1]
        if tuple(shipped.k.shape) != (layers, pos, heads, hd):
            raise kvship.ShipMismatchError(
                f"payload rows are {tuple(shipped.k.shape)} but this "
                f"engine expects [{layers}, {pos}, {heads}, {hd}]"
            )
        k, v, ks, vs = self._convert_wire(shipped)
        if self.paged:
            blocks_moved = self._import_paged(
                slot, ids, request, pos, k, v, ks, vs
            )
        else:
            blocks_moved = 0
            self.cache["k"] = self.cache["k"].at[:, slot, :pos].set(
                jnp.asarray(k))
            self.cache["v"] = self.cache["v"].at[:, slot, :pos].set(
                jnp.asarray(v))
            if self.mesh is not None:
                self.cache = self._shard_kv(self.cache)
        # prefill_step's activation tail, replicated: the one-shot
        # generate()'s key schedule from the request seed, the cursors
        # from the shipped emission count
        req = request
        temp = float(req.temperature)
        top_k = min(int(req.top_k), self.vocab_size)
        top_p = float(req.top_p)
        key = jax.random.key(int(req.seed))
        karr = jax.random.split(key)
        n = int(req.max_new_tokens)
        self._keys[slot] = (
            np.asarray(jax.random.key_data(jax.random.split(karr[0], n - 1)),
                       np.uint32)
            if n > 1 else np.zeros((0, 2), np.uint32)
        )
        self._step_idx[slot] = len(emitted) - 1
        self._pos[slot] = pos
        self._key_valid[slot] = 1
        self._tokens[slot] = emitted[-1]
        self._temp[slot] = temp
        self._topk[slot] = top_k
        self._topp[slot] = top_p
        self._active[slot] = 1
        self._slot_gen[slot] = self.deploy_generation
        self._spec_ok[slot] = bool(self.spec_k) and bool(
            getattr(req, "speculate", True)
        )
        if self._spec_ok[slot]:
            # the proposer's context is (prompt, emitted...) — replayed
            # here it reaches the exporter's exact state, and exact
            # acceptance keeps the stream bit-identical regardless of
            # what it proposes
            self.speculator.begin(slot, ids, emitted[0])
            if len(emitted) > 1:
                self.speculator.observe(slot, emitted[1:])
        self._dev = None
        self._prefills[slot] = None
        nbytes = shipped.k.nbytes + shipped.v.nbytes
        if shipped.ks is not None:
            nbytes += shipped.ks.nbytes + shipped.vs.nbytes
        c = self.kvship_counts
        c["import_requests"] += 1
        c["import_bytes"] += int(nbytes)
        c["import_blocks"] += blocks_moved
        c["import_seconds"] += time.perf_counter() - t0

    def kvship_stats(self) -> dict | None:
        """KV shipping meters for /metrics and the stats JSONL (None
        until the first ship touches this engine, so non-disaggregated
        replicas' outputs are unchanged). Flat scalars by design: the
        stats JSONL's nested-dict filter and ``summarize_run`` consume
        them directly."""
        c = self.kvship_counts
        if not (c["export_requests"] or c["import_requests"]):
            return None
        out = dict(c)
        out["export_seconds"] = round(out["export_seconds"], 6)
        out["import_seconds"] = round(out["import_seconds"], 6)
        return out

    # -- observability -------------------------------------------------------

    def prefix_stats(self) -> dict | None:
        """Prefix-cache counters for the serve gauges (None when the
        cache is disabled)."""
        return None if self.prefix_cache is None else self.prefix_cache.stats()

    def kv_stats(self) -> dict | None:
        """Block-pool gauges for /metrics and the stats JSONL (None in
        dense mode). ``kv_bytes`` is the arena's true HBM footprint;
        ``hist_blocks_per_request`` is the blocks-held distribution
        observed at release."""
        if not self.paged:
            return None
        ps = self.block_pool.stats()
        out = {
            **ps,
            "kv_dtype": self.kv_dtype or str(self.cfg.dtype),
            "block_evictions": self.kv_block_evictions,
            "kv_bytes": int(
                self.block_pool.num_blocks * self.kv_block_size
                * kv_bytes_per_token(self.cfg, self.kv_dtype)
            ),
            "hist_blocks_per_request": self.hist_blocks_per_request.snapshot(),
        }
        if self.tp > 1:
            # per-shard breakdown: the host pool is global (a block id
            # names the same physical block on every shard — each shard
            # holds that block's rows for ITS KV heads), so every shard
            # reports the same free count here; the per-shard family
            # exists so a fleet scraper has one shape whether shards
            # share a pool (this engine) or own one each (a future
            # disaggregated deployment)
            out["tp_degree"] = self.tp
            out["blocks_free_per_shard"] = {
                str(s): ps["blocks_free"] for s in range(self.tp)
            }
        return out

    def devtime_stats(self) -> dict:
        """Per-program device/compile-second ledgers for /metrics and
        the stats JSONL — the accountant is always armed (host-side
        perf_counter sections; observation-only)."""
        return self.accountant.snapshot()

    def blocks_held(self, slot: int) -> int:
        """KV blocks currently mapped into ``slot`` (0 in dense mode —
        a dense slot's cache rows are a fixed arena share, not a
        metered allocation). The scheduler's ``kv_block_seconds``
        attribution reads this at admission."""
        if not self.paged:
            return 0
        return len(self._slot_blocks[slot])

    def spec_stats(self) -> dict | None:
        """Speculative-decoding counters for /metrics and the stats
        JSONL (None with speculation off). ``acceptance_rate`` is
        accepted/drafted over the engine's whole life;
        ``tokens_per_tick_mean`` averages emitted tokens over
        SPECULATIVE ticks (the histogram carries the distribution)."""
        if not self.spec_k:
            return None
        drafted = self.spec_draft_tokens
        hist = self.hist_spec_tokens_per_tick.snapshot()
        return {
            "spec_k": self.spec_k,
            "spec_ngram": self.spec_ngram,
            "draft_tokens": drafted,
            "accepted_tokens": self.spec_accepted_tokens,
            "rejected_tokens": self.spec_rejected_tokens,
            "acceptance_rate": (
                round(self.spec_accepted_tokens / drafted, 4)
                if drafted else None
            ),
            "spec_ticks": self.spec_ticks,
            "decode_ticks": self.decode_ticks,
            "tokens_per_tick_mean": (
                round(hist["sum"] / hist["count"], 4)
                if hist["count"] else None
            ),
            "hist_tokens_per_tick": hist,
        }

    @property
    def kv_layout(self) -> str:
        """The engine's program layout tag: cache storage mode plus the
        tensor-parallel degree when sharded — the string every
        ``compile_counts`` key carries."""
        if not self.paged:
            base = "dense"
        elif self.kv_dtype == "int8":
            base = "paged-int8"
        else:
            base = "paged"
        return base if self.tp == 1 else f"{base}-tp{self.tp}"

    def compile_counts(self) -> dict:
        """Compiled-executable counts per program, keyed by
        ``kind:layout`` — the bounded-compile contract is testable, not
        folklore: chunk programs are capped by the power-of-two bucket
        set, decode/copy by 1 each (sampling is fused into chunk and
        decode, so there is no separate sample program to count).

        Keys are LAYOUT-QUALIFIED (``prefill_chunk:paged-int8-tp2``,
        not ``prefill_chunk``): a flat kind key let a per-layout pin
        silently read the wrong mode's count — a paged test asserting
        ``prefill_chunk <= 4`` could not tell whether it had measured
        the paged program set or the dense one. ``buckets`` records the
        (kind -> program shape) set actually dispatched, so a pin can
        assert the exact (kind, bucket, layout) triples too."""
        def size(fn):
            if fn is None:
                return None
            try:
                return fn._cache_size()
            except Exception:  # pragma: no cover - older/newer jit internals
                return None

        layout = self.kv_layout
        out: dict = {
            "layout": layout,
            "tp_degree": self.tp,
            "buckets": {k: sorted(v) for k, v in sorted(self._buckets.items())},
            f"prefill_chunk:{layout}": size(
                self._chunk_paged if self.paged else self._chunk
            ),
            f"decode:{layout}": size(
                self._decode_paged if self.paged else self._decode
            ),
        }
        if self._verify is not None:
            out[f"verify:{layout}"] = size(self._verify)
        if not self.paged:
            # the dense-only prefix-cache copy programs; paged mode
            # shares prefix blocks by reference and never compiles them
            out[f"extract:{layout}"] = size(self._extract)
            out[f"insert:{layout}"] = size(self._insert)
        return out
