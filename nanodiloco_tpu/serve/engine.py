"""Slot-based continuous-batching engine over the static-shape KV cache.

Orca-style (Yu et al., OSDI'22) iteration-level scheduling on TPU terms:
the engine owns ONE preallocated cache ``[L, B, S_max, Hkv, hd]`` whose
B rows are independent request slots. A request's life:

- ``prefill(slot, request)`` runs the prompt through the SAME cached
  prefill program the one-shot ``generate`` uses, writing K/V into the
  slot's cache row at positions ``[0, P)``, and samples the first token.
- every ``step()`` advances ALL slots one token with a single compiled
  program (per-slot positions, PRNG keys, and sampling params are traced
  arrays) — admitting a new request or retiring a finished one never
  recompiles and never stops the other slots' streams.
- ``release(slot)`` frees the row. Nothing is zeroed: a retired slot's
  stale K/V is causally unreachable to the next occupant (its prefill
  overwrites ``[0, P)`` and decode never attends past its own position).

Determinism contract (tested): a request's token stream is exactly the
stream ``generate()`` produces alone with the same seed and sampling
params. The per-request PRNG schedule is replicated on the host at
admission — ``key, k0 = split(key(seed))`` for the first token, then
``split(key, max_new_tokens - 1)`` for the decode steps (the full array
is materialized up front because ``split(key, n)[i]`` depends on ``n``
on this jax) — and each tick feeds every slot its own next key.

Known divergence, inherited from ``generate`` and narrowed here: dense-
dispatch token-choice MoE sizes expert capacity from the tokens in the
call, so a decode tick routes over B slots where ``generate`` routes
over 1. With ample capacity (or ``moe_dispatch="ragged"``) routing is
per-token independent and identical; dead slots are masked out of
routing entirely (``active``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from nanodiloco_tpu.models.config import LlamaConfig
from nanodiloco_tpu.models.generate import (
    decode_slots_fn,
    init_kv_cache,
    prefill_slot_fn,
)


class InferenceEngine:
    """The slot backend the scheduler drives. Not thread-safe: all calls
    must come from one thread (the scheduler's tick loop)."""

    def __init__(
        self,
        params,
        cfg: LlamaConfig,
        *,
        num_slots: int = 4,
        max_len: int = 1024,
    ) -> None:
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1; got {num_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2; got {max_len}")
        if cfg.num_experts and cfg.router_type == "experts_choose":
            raise ValueError(
                "expert-choice routing is training-only (see generate()); "
                "use router_type='tokens_choose' for serving"
            )
        self.params = params
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.vocab_size = cfg.vocab_size
        self.cache = init_kv_cache(cfg, self.num_slots, self.max_len)
        self._prefill = prefill_slot_fn(cfg)
        self._decode = decode_slots_fn(cfg)

        b, s = self.num_slots, self.max_len
        self._tokens = np.zeros(b, np.int32)       # next input token per slot
        self._pos = np.zeros(b, np.int32)          # next cache write position
        self._key_valid = np.zeros((b, s), np.int32)
        self._active = np.zeros(b, np.int32)
        self._temp = np.zeros(b, np.float32)
        self._topk = np.zeros(b, np.int32)
        self._topp = np.ones(b, np.float32)
        # per-slot precomputed decode key data [max_new-1, 2] uint32
        self._keys: list[np.ndarray | None] = [None] * b
        self._step_idx = [0] * b
        self._dummy_key = np.asarray(
            jax.random.key_data(jax.random.key(0)), np.uint32
        )
        # device-resident copies of the slot state that only changes at
        # admit/release (key_valid alone is [B, S_max] — re-uploading it
        # every tick would put an H2D transfer on the per-token path)
        self._dev: dict | None = None

    # -- request validation (shared with the server's 400 path) -------------

    def validate(self, prompt, max_new_tokens: int) -> None:
        """Raises ValueError when a request cannot be served by this
        engine's static shapes."""
        if len(prompt) < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1; got {max_new_tokens}"
            )
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)} tokens) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine's max_len "
                f"({self.max_len})"
            )
        bad = [t for t in prompt if not 0 <= int(t) < self.vocab_size]
        if bad:
            raise ValueError(
                f"prompt tokens {bad[:4]} outside the model vocabulary "
                f"({self.vocab_size})"
            )

    # -- slot lifecycle ------------------------------------------------------

    def prefill(self, slot: int, request) -> int:
        """Admit ``request`` into ``slot``: write its prompt K/V, stage
        its sampling state, and return the first sampled token."""
        ids = list(request.prompt)
        self.validate(ids, request.max_new_tokens)
        p = len(ids)
        temp = float(request.temperature)
        top_k = min(int(request.top_k), self.vocab_size)
        top_p = float(request.top_p)

        # the one-shot generate()'s exact key schedule, replayed per slot
        key = jax.random.key(int(request.seed))
        karr = jax.random.split(key)  # karr[0] = rest, karr[1] = k0
        tok0, self.cache = self._prefill(
            self.params, self.cache,
            jnp.asarray([ids], jnp.int32), jnp.ones((1, p), jnp.int32),
            jnp.int32(slot), karr[1],
            jnp.float32(temp), jnp.int32(top_k), jnp.float32(top_p),
        )
        n = int(request.max_new_tokens)
        self._keys[slot] = (
            np.asarray(jax.random.key_data(jax.random.split(karr[0], n - 1)),
                       np.uint32)
            if n > 1 else np.zeros((0, 2), np.uint32)
        )
        self._step_idx[slot] = 0
        self._pos[slot] = p
        self._key_valid[slot] = 1
        self._tokens[slot] = int(tok0)
        self._temp[slot] = temp
        self._topk[slot] = top_k
        self._topp[slot] = top_p
        self._active[slot] = 1
        self._dev = None  # slot state changed: re-stage on the next step
        return int(tok0)

    def step(self) -> np.ndarray:
        """Advance every slot one token (one compiled tick). Returns the
        [B] sampled tokens; entries for inactive slots are meaningless."""
        b = self.num_slots
        keys_now = np.empty((b, 2), np.uint32)
        for s in range(b):
            ks = self._keys[s]
            if self._active[s] and ks is not None and self._step_idx[s] < len(ks):
                keys_now[s] = ks[self._step_idx[s]]
            else:
                keys_now[s] = self._dummy_key
        if self._dev is None:
            self._dev = {
                "key_valid": jnp.asarray(self._key_valid),
                "temp": jnp.asarray(self._temp),
                "topk": jnp.asarray(self._topk),
                "topp": jnp.asarray(self._topp),
                "active": jnp.asarray(self._active),
            }
        nxt, self.cache = self._decode(
            self.params, self.cache,
            jnp.asarray(self._tokens), jnp.asarray(self._pos),
            self._dev["key_valid"], jnp.asarray(keys_now),
            self._dev["temp"], self._dev["topk"],
            self._dev["topp"], self._dev["active"],
        )
        nxt = np.asarray(nxt)
        for s in range(b):
            if self._active[s]:
                self._pos[s] += 1
                self._step_idx[s] += 1
                self._tokens[s] = nxt[s]
        return nxt

    def release(self, slot: int) -> None:
        self._active[slot] = 0
        self._key_valid[slot] = 0
        self._keys[slot] = None
        self._pos[slot] = 0
        self._tokens[slot] = 0
        self._dev = None
