"""Slot-based continuous-batching engine over the static-shape KV cache.

Orca-style (Yu et al., OSDI'22) iteration-level scheduling on TPU terms:
the engine owns ONE preallocated cache ``[L, B, S_max, Hkv, hd]`` whose
B rows are independent request slots. A request's life:

- ``start_prefill(slot, request)`` stages the request into a free slot
  and, when the prefix cache holds the prompt's leading chunks, copies
  their K/V rows in so only the suffix needs compute.
- ``prefill_step(slot)`` runs ONE prefill chunk (Sarathi-Serve,
  arXiv:2403.02310: chunked prefill is what keeps a 4k-token prompt
  from freezing every live decode stream between two ticks). The final
  chunk samples and returns the first token; earlier chunks return
  None. Chunk lengths are bucketed to powers of two, so mixed-length
  traffic compiles a BOUNDED program set — not one prefill executable
  per prompt length.
- every ``step()`` advances ALL decoding slots one token with a single
  compiled program (per-slot positions, PRNG keys, and sampling params
  ride as traced arrays) — admitting a new request or retiring a
  finished one never recompiles and never stops the other streams.
- ``release(slot)`` frees the row (mid-prefill or mid-decode). Nothing
  is zeroed: a retired slot's stale K/V is causally unreachable to the
  next occupant (its prefill overwrites ``[0, P)`` and decode never
  attends past its own position).

Chunking math (why it is exact): K/V at position i depend only on
``tokens[:i+1]``, so writing them chunk-by-chunk produces the same cache
bits as one whole-prompt call; each chunk's queries attend causally over
everything already written, which is the same reduction the one-shot
prefill performs row by row. The final chunk is bucketed by RE-FEEDING
the prompt's last ``bucket`` tokens (recomputing K/V to identical bits)
so its last row is the true last prompt token — except a single-chunk
prompt shorter than its bucket, which right-pads instead and passes the
last REAL index into the program (pad K/V land past the prompt,
causally unreachable, then overwritten by decode).

Determinism contract (tested): a request's token stream is exactly the
stream ``generate()`` produces alone with the same seed and sampling
params — through chunked admission AND through a prefix-cache hit (the
cached rows were computed from the same tokens at the same positions
under the same params). The per-request PRNG schedule is replicated on
the host at admission — ``key, k0 = split(key(seed))`` for the first
token, then ``split(key, max_new_tokens - 1)`` for the decode steps
(the full array is materialized up front because ``split(key, n)[i]``
depends on ``n`` on this jax) — and each tick feeds every slot its own
next key.

Known divergence, inherited from ``generate`` and narrowed here: dense-
dispatch token-choice MoE sizes expert capacity from the tokens in the
call, so a decode tick routes over B slots where ``generate`` routes
over 1, and a prefill chunk routes over its chunk where ``generate``
routes over the whole prompt. With ample capacity (or
``moe_dispatch="ragged"``) routing is per-token independent and
identical; dead slots are masked out of routing entirely (``active``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from nanodiloco_tpu.models.config import LlamaConfig
from nanodiloco_tpu.models.generate import (
    decode_slots_fn,
    extract_chunk_fn,
    init_kv_cache,
    insert_chunk_fn,
    prefill_chunk_fn,
    sample_token_fn,
)
from nanodiloco_tpu.serve.prefix_cache import PrefixCache


def _floor_pow2(n: int) -> int:
    return 1 << (int(n).bit_length() - 1)


def _ceil_pow2(n: int) -> int:
    return 1 << (int(n) - 1).bit_length() if n > 1 else 1


@dataclasses.dataclass
class _Prefill:
    """One slot's in-flight prefill: the staged request plus the cursor
    into its prompt. ``done`` tokens are already in the slot's cache
    (prefix-cache hit + completed chunks); the chunks-remaining count
    lives in the scheduler's ``_Prefilling``, fed by ``start_prefill``'s
    return value."""

    request: object
    ids: list[int]
    done: int            # prompt tokens whose K/V are written


class InferenceEngine:
    """The slot backend the scheduler drives. Not thread-safe: all calls
    must come from one thread (the scheduler's tick loop)."""

    def __init__(
        self,
        params,
        cfg: LlamaConfig,
        *,
        num_slots: int = 4,
        max_len: int = 1024,
        chunk_size: int = 64,
        prefix_cache_tokens: int = 0,
    ) -> None:
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1; got {num_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2; got {max_len}")
        if chunk_size < 1:
            raise ValueError(f"chunk_size must be >= 1; got {chunk_size}")
        if cfg.num_experts and cfg.router_type == "experts_choose":
            raise ValueError(
                "expert-choice routing is training-only (see generate()); "
                "use router_type='tokens_choose' for serving"
            )
        self.params = params
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        # chunk lengths are bucketed to powers of two; capping the top
        # bucket at the largest power of two <= max_len keeps every
        # bucketed write inside the slot row (a bucket can right-pad a
        # single-chunk prompt, and dynamic_update_slice would CLAMP an
        # out-of-range write backwards over real positions)
        self.chunk_size = _floor_pow2(min(int(chunk_size), self.max_len))
        self.vocab_size = cfg.vocab_size
        self.cache = init_kv_cache(cfg, self.num_slots, self.max_len)
        self._chunk = prefill_chunk_fn(cfg)
        self._sample = sample_token_fn(cfg)
        self._decode = decode_slots_fn(cfg)
        self._extract = extract_chunk_fn(cfg)
        self._insert = insert_chunk_fn(cfg)
        self.prefix_cache = (
            PrefixCache(int(prefix_cache_tokens), self.chunk_size)
            if prefix_cache_tokens else None
        )

        b, s = self.num_slots, self.max_len
        self._tokens = np.zeros(b, np.int32)       # next input token per slot
        self._pos = np.zeros(b, np.int32)          # next cache write position
        self._key_valid = np.zeros((b, s), np.int32)
        self._active = np.zeros(b, np.int32)
        self._temp = np.zeros(b, np.float32)
        self._topk = np.zeros(b, np.int32)
        self._topp = np.ones(b, np.float32)
        # per-slot precomputed decode key data [max_new-1, 2] uint32
        self._keys: list[np.ndarray | None] = [None] * b
        self._step_idx = [0] * b
        self._prefills: list[_Prefill | None] = [None] * b
        self._dummy_key = np.asarray(
            jax.random.key_data(jax.random.key(0)), np.uint32
        )
        # device-resident copies of the slot state that only changes at
        # admit/release (key_valid alone is [B, S_max] — re-uploading it
        # every tick would put an H2D transfer on the per-token path)
        self._dev: dict | None = None

    # -- request validation (shared with the server's 400 path) -------------

    def validate(self, prompt, max_new_tokens: int) -> None:
        """Raises ValueError when a request cannot be served by this
        engine's static shapes."""
        if len(prompt) < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1; got {max_new_tokens}"
            )
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)} tokens) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine's max_len "
                f"({self.max_len})"
            )
        bad = [t for t in prompt if not 0 <= int(t) < self.vocab_size]
        if bad:
            raise ValueError(
                f"prompt tokens {bad[:4]} outside the model vocabulary "
                f"({self.vocab_size})"
            )

    # -- slot lifecycle ------------------------------------------------------

    def start_prefill(self, slot: int, request) -> int:
        """Stage ``request`` into free slot ``slot``: validate, reuse
        any cached shared-prefix K/V, and return the number of prefill
        chunks still to run (>= 1 — the last prompt token always
        prefills for real, its logits seed the first sample)."""
        ids = [int(t) for t in request.prompt]
        self.validate(ids, request.max_new_tokens)
        done = 0
        use_cache = self.prefix_cache is not None and getattr(
            request, "prefix_cache", True
        )
        if use_cache:
            blocks = self.prefix_cache.match(ids)
            for i, (k, v) in enumerate(blocks):
                self.cache = self._insert(
                    self.cache, k, v, jnp.int32(slot),
                    jnp.int32(i * self.chunk_size),
                )
            done = len(blocks) * self.chunk_size
        self._prefills[slot] = _Prefill(request, ids, done)
        return -(-(len(ids) - done) // self.chunk_size)

    def prefill_step(self, slot: int) -> int | None:
        """Run ONE prefill chunk for the staged request in ``slot``.
        Returns None while chunks remain; the final chunk samples and
        returns the first token, leaving the slot live for ``step()``."""
        pf = self._prefills[slot]
        if pf is None:
            raise ValueError(f"slot {slot} has no prefill in flight")
        ids, p = pf.ids, len(pf.ids)
        remaining = p - pf.done
        if remaining > self.chunk_size:
            # full interior chunk: exactly chunk_size real tokens
            lo = pf.done
            chunk = ids[lo:lo + self.chunk_size]
            _logits, self.cache = self._chunk(
                self.params, self.cache,
                jnp.asarray([chunk], jnp.int32),
                jnp.ones((1, self.chunk_size), jnp.int32),
                jnp.int32(slot), jnp.int32(lo),
                jnp.int32(self.chunk_size - 1),
            )
            pf.done += self.chunk_size
            return None

        # final chunk, bucketed to a power of two. Prefer re-feeding the
        # prompt's last `bucket` real tokens (recomputed K/V bits are
        # identical, and the last row IS the last prompt token); a
        # single-chunk prompt shorter than its bucket right-pads instead
        # and passes the true last index.
        bucket = _ceil_pow2(remaining)
        if p >= bucket:
            lo = p - bucket
            chunk = ids[lo:]
            valid = np.ones((1, bucket), np.int32)
            last = bucket - 1
        else:  # pf.done == 0 and the whole prompt is shorter than bucket
            lo = 0
            chunk = ids + [0] * (bucket - p)
            valid = np.zeros((1, bucket), np.int32)
            valid[0, :p] = 1
            last = p - 1
        logits, self.cache = self._chunk(
            self.params, self.cache,
            jnp.asarray([chunk], jnp.int32), jnp.asarray(valid),
            jnp.int32(slot), jnp.int32(lo), jnp.int32(last),
        )
        pf.done = p
        req = pf.request
        temp = float(req.temperature)
        top_k = min(int(req.top_k), self.vocab_size)
        top_p = float(req.top_p)
        # the one-shot generate()'s exact key schedule, replayed per slot
        key = jax.random.key(int(req.seed))
        karr = jax.random.split(key)  # karr[0] = rest, karr[1] = k0
        tok0 = int(self._sample(
            logits, karr[1],
            jnp.float32(temp), jnp.int32(top_k), jnp.float32(top_p),
        ))
        n = int(req.max_new_tokens)
        self._keys[slot] = (
            np.asarray(jax.random.key_data(jax.random.split(karr[0], n - 1)),
                       np.uint32)
            if n > 1 else np.zeros((0, 2), np.uint32)
        )
        self._step_idx[slot] = 0
        self._pos[slot] = p
        self._key_valid[slot] = 1
        self._tokens[slot] = tok0
        self._temp[slot] = temp
        self._topk[slot] = top_k
        self._topp[slot] = top_p
        self._active[slot] = 1
        self._dev = None  # slot state changed: re-stage on the next step

        self._prefills[slot] = None
        if (
            self.prefix_cache is not None
            and getattr(req, "prefix_cache", True)
        ):
            # explicit admission: every completed (non-opted-out)
            # prefill offers its whole-chunk prefix; only chunks not
            # already cached are copied off the slot's rows
            cs = self.chunk_size

            def extract(i: int):
                k, v = self._extract(
                    self.cache, jnp.int32(slot), jnp.int32(i * cs), cs
                )
                return k, v

            self.prefix_cache.insert(ids, (p - 1) // cs, extract)
        return tok0

    def prefill(self, slot: int, request) -> int:
        """Whole-prompt convenience: stage and run every chunk in one
        call (the parity tests' sequential driver; the scheduler
        interleaves ``prefill_step`` with decode ticks instead)."""
        self.start_prefill(slot, request)
        while True:
            tok = self.prefill_step(slot)
            if tok is not None:
                return tok

    def step(self) -> np.ndarray:
        """Advance every live slot one token (one compiled tick).
        Returns the [B] sampled tokens; entries for inactive slots are
        meaningless."""
        b = self.num_slots
        keys_now = np.empty((b, 2), np.uint32)
        for s in range(b):
            ks = self._keys[s]
            if self._active[s] and ks is not None and self._step_idx[s] < len(ks):
                keys_now[s] = ks[self._step_idx[s]]
            else:
                keys_now[s] = self._dummy_key
        if self._dev is None:
            self._dev = {
                "key_valid": jnp.asarray(self._key_valid),
                "temp": jnp.asarray(self._temp),
                "topk": jnp.asarray(self._topk),
                "topp": jnp.asarray(self._topp),
                "active": jnp.asarray(self._active),
            }
        nxt, self.cache = self._decode(
            self.params, self.cache,
            jnp.asarray(self._tokens), jnp.asarray(self._pos),
            self._dev["key_valid"], jnp.asarray(keys_now),
            self._dev["temp"], self._dev["topk"],
            self._dev["topp"], self._dev["active"],
        )
        nxt = np.asarray(nxt)
        for s in range(b):
            if self._active[s]:
                self._pos[s] += 1
                self._step_idx[s] += 1
                self._tokens[s] = nxt[s]
        return nxt

    def release(self, slot: int) -> None:
        self._active[slot] = 0
        self._key_valid[slot] = 0
        self._keys[slot] = None
        self._pos[slot] = 0
        self._tokens[slot] = 0
        self._prefills[slot] = None
        self._dev = None

    # -- observability -------------------------------------------------------

    def prefix_stats(self) -> dict | None:
        """Prefix-cache counters for the serve gauges (None when the
        cache is disabled)."""
        return None if self.prefix_cache is None else self.prefix_cache.stats()

    def compile_counts(self) -> dict:
        """Compiled-executable counts per program — the bounded-compile
        contract is testable, not folklore: chunk programs are capped by
        the power-of-two bucket set, decode/sample/copy by 1 each."""
        def size(fn):
            try:
                return fn._cache_size()
            except Exception:  # pragma: no cover - older/newer jit internals
                return None

        return {
            "prefill_chunk": size(self._chunk),
            "decode": size(self._decode),
            "sample": size(self._sample),
            "extract": size(self._extract),
            "insert": size(self._insert),
        }
