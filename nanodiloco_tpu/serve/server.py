"""Stdlib HTTP serving daemon over the scheduler + engine.

Same pattern and lifecycle as the training telemetry endpoint
(``obs/telemetry.py``): ``http.server`` on daemon threads, no new
dependencies, ``port=0`` picks a free port exposed as ``.port``. The
server owns the scheduler's tick loop on one dedicated thread; HTTP
handler threads only ``submit`` and wait on their ticket, so the
engine is single-threaded by construction.

Endpoints:
- ``POST /v1/generate`` — JSON in: ``{"prompt": str}`` or
  ``{"token_ids": [int]}`` plus optional ``max_new_tokens``,
  ``temperature``, ``top_k``, ``top_p``, ``seed``, ``stop`` (bool:
  finish at the tokenizer's EOS, default true), ``stop_token`` (int
  override), ``deadline_s``, ``priority`` (SLO class 0-9, 0 = most
  urgent, default 1 — admission is EDF within a class), and
  ``prefix_cache`` (bool, default true: opt this request out of
  shared-prefix KV reuse). JSON out: generated ``text`` (when a
  tokenizer is configured) + ``token_ids`` (truncated at the stop
  token, like the ``generate`` CLI) + ``finish_reason`` + ``timing``
  (queued/TTFT/decode seconds). 400 on a malformed request, 429 when
  the admission queue is full (backpressure — the client retries
  later), 503 once the engine loop has died.
- ``GET /healthz`` — LIVENESS: 200 while the tick loop is alive, 503
  after it died; body carries queue depth, slot occupancy, the KV
  block-pool free count, and the deploy generation (the fleet router's
  routing inputs). ``?ready=1`` answers the READINESS contract instead.
- ``GET /readyz`` — READINESS: 200 only when the loop is alive AND the
  scheduler is not draining. A replica draining for a weight push is
  alive-but-not-ready — the router must route around it, not eject it
  as dead (liveness and readiness are different questions, and
  conflating them turns every deploy into a false crash).
- ``POST /v1/cancel`` — ``{"request_id": str}``: cancel that in-flight
  stream through the scheduler's ticket-cancel path (slot and paged KV
  blocks free at the next tick). The fleet router's hedge-loser and
  deadline-expiry cleanup; 404 when nothing by that id is in flight.
- ``POST /admin/drain`` / ``POST /admin/resume`` — stop/resume
  admission (in-flight streams always finish); the fleet router brackets
  a weight push with these.
- ``POST /admin/swap`` — ``{"checkpoint_dir": str, "step": int?}``:
  load that checkpoint's merged snapshot (the ``restore_raw``
  self-describing path) and hot-swap it into the engine between ticks
  (``swap_weights``) — the KV pool survives, in-flight streams finish
  on the old weights, the prefix cache is invalidated. 404 unless the
  server was built with a ``swap_loader`` (the serve CLI wires one; a
  bare embedded server is not remotely re-weightable by default).
- ``GET /metrics`` — OpenMetrics serve gauges (queue depth, slot
  occupancy, TTFT last/p50/p95, decode tokens/s), counters (requests
  by outcome, tokens), and real histograms (cumulative buckets +
  ``_count``/``_sum`` for TTFT, queue wait, per-tick decode latency),
  rendered by the same ``render_exposition`` the training telemetry
  endpoint uses.
- ``POST /debug/profile?seconds=N`` — capture a ``jax.profiler`` trace
  of the live serving process (``profile_dir`` opt-in; 404 without it,
  409 while a capture runs) — the on-demand twin of the training
  telemetry endpoint's.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from nanodiloco_tpu.obs.devtime import devtime_families
from nanodiloco_tpu.obs.telemetry import (
    OPENMETRICS_CONTENT_TYPE,
    handle_profile_request,
    render_exposition,
)
from nanodiloco_tpu.obs.tracer import TraceContext
from nanodiloco_tpu.serve import kvship
from nanodiloco_tpu.serve.scheduler import (
    ClassShed,
    GenRequest,
    QueueFull,
    Scheduler,
)


class ServeServer:
    """HTTP front end + tick-loop owner. ``tokenizer`` is optional: with
    one, ``prompt`` strings are accepted and ``text`` is returned, and
    its EOS id is the default stop token; without, clients send
    ``token_ids``."""

    def __init__(
        self,
        scheduler: Scheduler,
        tokenizer=None,
        *,
        port: int = 0,
        host: str = "0.0.0.0",
        default_max_new_tokens: int = 64,
        max_new_tokens_cap: int = 256,
        request_timeout_s: float = 600.0,
        default_deadline_s: float | None = None,
        idle_sleep_s: float = 0.002,
        profile_dir: str | None = None,
        swap_loader=None,
        swap_timeout_s: float = 120.0,
        tick_delay_s: float = 0.0,
        role: str = "both",
    ) -> None:
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill', 'decode', or 'both'; got {role!r}"
            )
        self._scheduler = scheduler
        self._tokenizer = tokenizer
        # disaggregated-serving tier (fleet/disagg.py): declared in the
        # health body so the router can route admissions to the prefill
        # tier and handoffs to the decode tier. "both" (the default) is
        # a monolithic replica — eligible for either.
        self.role = role
        # POST /debug/profile?seconds=N target directory (None = the
        # endpoint answers 404; live profiling is an operator opt-in)
        self.profile_dir = profile_dir
        # POST /admin/swap loader: (checkpoint_dir, step|None) -> params
        # matching the engine's serving config (raise ValueError when it
        # does not — the handler's 400). None = the endpoint answers 404.
        self._swap_loader = swap_loader
        self._swap_timeout_s = float(swap_timeout_s)
        self._default_new = int(default_max_new_tokens)
        self._cap_new = int(max_new_tokens_cap)
        self._timeout_s = float(request_timeout_s)
        self._default_deadline_s = default_deadline_s
        self._idle_sleep_s = float(idle_sleep_s)
        # straggler INJECTION (serve --inject-tick-delay-s): sleep this
        # long before every scheduling tick, inflating TTFT and decode
        # latency without touching correctness — the serve-side twin of
        # the trainer's stall fault (resilience/faults), used by the
        # SLO drill (chip_agenda slo_watch) to make one replica burn
        # its latency budget while staying alive and routable
        self._tick_delay_s = float(tick_delay_s)
        self._stop = threading.Event()
        self._loop_thread: threading.Thread | None = None
        self._http_thread: threading.Thread | None = None
        self._loop_error: str | None = None
        # in-flight tickets by request_id, for POST /v1/cancel (the
        # fleet router's hedge-loser / departed-client path): cancel
        # rides the scheduler's existing ticket-cancel machinery, so a
        # cancelled stream frees its slot and paged KV blocks instead
        # of decoding tokens nobody will read
        self._inflight: dict[str, object] = {}
        self._inflight_lock = threading.Lock()

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # scrapes must not spam stdout
                pass

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, code: int, doc: dict) -> None:
                self._reply(code, (json.dumps(doc) + "\n").encode(),
                            "application/json")

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    self._reply(200, server.render_metrics().encode(),
                                OPENMETRICS_CONTENT_TYPE)
                elif path == "/readyz" or (
                    # parsed, not substring-matched: a stray query
                    # whose TEXT contains "ready=1" (?thready=1) must
                    # not silently flip a liveness probe to readiness
                    path == "/healthz"
                    and "1" in parse_qs(query).get("ready", [])
                ):
                    code, doc = server.readiness()
                    self._reply_json(code, doc)
                elif path == "/healthz":
                    code, doc = server.health()
                    self._reply_json(code, doc)
                else:
                    self._reply(404, b"not found\n", "text/plain")

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                if path == "/debug/profile":
                    code, out = handle_profile_request(
                        server.profile_dir, self.path
                    )
                    self._reply_json(code, out)
                    return
                if path in ("/admin/drain", "/admin/resume", "/admin/swap",
                            "/admin/admission", "/admin/kv/export",
                            "/admin/kv/import"):
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        doc = json.loads(self.rfile.read(n) or b"{}")
                        if not isinstance(doc, dict):
                            raise ValueError("body must be a JSON object")
                    except ValueError as e:
                        self._reply_json(400, {"error": f"bad JSON: {e}"})
                        return
                    code, out = server.handle_admin(path, doc)
                    self._reply_json(code, out)
                    return
                if path not in ("/v1/generate", "/v1/cancel"):
                    self._reply(404, b"not found\n", "text/plain")
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    doc = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(doc, dict):
                        raise ValueError("request body must be a JSON object")
                except ValueError as e:
                    self._reply_json(400, {"error": f"bad JSON: {e}"})
                    return
                if path == "/v1/cancel":
                    code, out = server.handle_cancel(doc)
                else:
                    code, out = server.handle_generate(doc)
                self._reply_json(code, out)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServeServer":
        # engine loop FIRST: the socket already accepts connections from
        # __init__, and a request handled before the loop thread exists
        # would get a spurious 503 from loop_alive()
        if self._loop_thread is None:
            self._loop_thread = threading.Thread(
                target=self._loop, name="nanodiloco-serve-engine", daemon=True,
            )
            self._loop_thread.start()
        if self._http_thread is None:
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="nanodiloco-serve-http", daemon=True,
            )
            self._http_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10)
            self._loop_thread = None
        if self._http_thread is not None:
            self._http_thread.join(timeout=5)
            self._http_thread = None

    def _loop(self) -> None:
        """The engine's single driver thread: tick until stopped; idle
        politely when no slot is live and the queue is empty."""
        while not self._stop.is_set():
            if self._tick_delay_s > 0:
                time.sleep(self._tick_delay_s)
            try:
                live = self._scheduler.tick()
            except Exception as e:
                # a dead loop must flip /healthz to 503, not vanish —
                # and its black box must land on disk: the engine
                # thread's death is exactly the event no clean-exit
                # exporter will ever see (obs/flightrec)
                self._loop_error = f"{type(e).__name__}: {e}"
                try:
                    from nanodiloco_tpu.obs import flightrec

                    flightrec.record_event(
                        "serve_loop_death", error=self._loop_error
                    )
                    flightrec.dump_current(
                        f"serve_loop:{type(e).__name__}"
                    )
                except Exception:
                    pass
                return
            if live == 0 and (
                self._scheduler.queue_depth() == 0
                or getattr(self._scheduler, "draining", False)
            ):
                # a draining scheduler admits nothing: spinning on a
                # non-empty queue would be a busy loop going nowhere
                time.sleep(self._idle_sleep_s)

    def loop_alive(self) -> bool:
        t = self._loop_thread
        return t is not None and t.is_alive() and self._loop_error is None

    # -- request handling ----------------------------------------------------

    def handle_generate(self, doc: dict) -> tuple[int, dict]:
        if not self.loop_alive():
            return 503, {"error": "engine loop is not running",
                         "detail": self._loop_error}
        try:
            request = self._parse_request(doc)
        except (ValueError, TypeError) as e:  # TypeError: e.g. int(None)
            return 400, {"error": str(e)}
        try:
            ticket = self._scheduler.submit(request)
        except ClassShed as e:
            # overload SHED, not backpressure: the body says so
            # explicitly ("shed": true + the sacrificed class) because
            # the two 429s demand opposite client behavior — a busy 429
            # is retried on another replica by the fleet router, a shed
            # 429 is fleet policy and terminal
            return 429, {
                "error": str(e),
                "shed": True,
                "shed_class": e.shed_class,
                "max_priority": e.max_priority,
            }
        except QueueFull as e:
            return 429, {"error": str(e)}
        return self._await_ticket(request, ticket)

    def _await_ticket(self, request: GenRequest,
                      ticket) -> tuple[int, dict]:
        """Wait a submitted ticket out and format the HTTP answer — the
        shared tail of /v1/generate and /admin/kv/import (an imported
        stream is an in-flight request like any other: cancellable by
        id, deadline-bounded, same result shape)."""
        # register for /v1/cancel under the SAME id the scheduler will
        # echo (client-supplied, or the scheduler's req-<rid> fallback);
        # a duplicate id overwrites — cancel then targets the newest
        rid_key = request.request_id or f"req-{ticket.rid}"
        with self._inflight_lock:
            self._inflight[rid_key] = ticket
        try:
            deadline = request.deadline_s
            timeout = self._timeout_s if deadline is None else deadline + 5.0
            result = ticket.wait(timeout)
        finally:
            with self._inflight_lock:
                if self._inflight.get(rid_key) is ticket:
                    del self._inflight[rid_key]
        if result is None:
            # nobody is left to read the stream: cancel so the scheduler
            # frees the slot instead of decoding to completion
            ticket.cancel()
            return 504, {"error": f"request timed out after {timeout:.0f}s"}
        if result["finish_reason"] == "error":
            # client mistakes were already rejected with 400 at parse
            # time (backend.validate); a prefill failure here is a
            # server-side fault (OOM, corrupt params) — 5xx, retryable
            return 500, {"error": result.get("error", "engine prefill failed")}
        tokens = result["tokens"]
        if request.stop_token is not None and request.stop_token in tokens:
            tokens = tokens[: tokens.index(request.stop_token)]
        out = {
            "id": result["rid"],
            # the join key across client logs, serve trace spans, and
            # the latency histograms: client-supplied or scheduler-
            # assigned, always echoed
            "request_id": result["request_id"],
            "finish_reason": result["finish_reason"],
            "token_ids": tokens,
            "prompt_tokens": len(request.prompt),
            "completion_tokens": len(tokens),
            "timing": {
                "queued_s": result["queued_s"],
                "ttft_s": result["ttft_s"],
                "decode_s": result["decode_s"],
                "total_s": result["total_s"],
                # attribution: this request's apportioned share of
                # dispatch seconds and its KV residency bill — the
                # per-request cost line, summable against the engine's
                # per-program device-second counters
                "prefill_device_s": result.get("prefill_device_s", 0.0),
                "decode_device_s": result.get("decode_device_s", 0.0),
                "kv_block_seconds": result.get("kv_block_seconds", 0.0),
            },
        }
        if self._tokenizer is not None:
            out["text"] = self._tokenizer.decode([int(t) for t in tokens])
        # echo the causal trace id for sampled requests — the client
        # (or router) needs it to find this request's spans; unsampled
        # and malformed contexts stay silent, same as the span path
        if request.trace_context:
            wire = TraceContext.from_wire(request.trace_context)
            if wire is not None and wire.sampled:
                out["trace_id"] = wire.trace_id
        return 200, out

    def handle_cancel(self, doc: dict) -> tuple[int, dict]:
        """POST /v1/cancel: ``{"request_id": str}`` — cancel an
        in-flight stream by its join key. The fleet router's hedge
        loser and deadline-expired paths land here; the scheduler's
        ticket-cancel machinery frees the slot and paged KV blocks at
        the next tick. 404 (``cancelled: false``) when nothing by that
        id is in flight — already finished, or never arrived."""
        rid = doc.get("request_id")
        if not isinstance(rid, str) or not rid:
            return 400, {"error": "request_id must be a non-empty string"}
        with self._inflight_lock:
            ticket = self._inflight.get(rid)
        if ticket is None:
            return 404, {"cancelled": False, "request_id": rid}
        ticket.cancel()
        return 200, {"cancelled": True, "request_id": rid}

    def _parse_request(self, doc: dict) -> GenRequest:
        if "token_ids" in doc:
            ids = doc["token_ids"]
            if (not isinstance(ids, list) or not ids
                    or not all(isinstance(t, int) for t in ids)):
                raise ValueError("token_ids must be a non-empty list of ints")
        elif "prompt" in doc:
            if self._tokenizer is None:
                raise ValueError(
                    "this server has no tokenizer; send token_ids"
                )
            if not isinstance(doc["prompt"], str) or not doc["prompt"]:
                raise ValueError("prompt must be a non-empty string")
            ids = self._tokenizer.encode(doc["prompt"])
            if not ids:
                raise ValueError("prompt is empty after tokenization")
        else:
            raise ValueError("request needs 'prompt' or 'token_ids'")
        max_new = int(doc.get("max_new_tokens", self._default_new))
        if not 1 <= max_new <= self._cap_new:
            raise ValueError(
                f"max_new_tokens must be in [1, {self._cap_new}]; got {max_new}"
            )
        temperature = float(doc.get("temperature", 0.0))
        if temperature < 0.0:
            raise ValueError(f"temperature must be >= 0; got {temperature}")
        top_k = int(doc.get("top_k", 0))
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0; got {top_k}")
        top_p = float(doc.get("top_p", 1.0))
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1]; got {top_p}")
        stop_token = doc.get("stop_token")
        if stop_token is None and doc.get("stop", True):
            stop_token = getattr(self._tokenizer, "eos_id", None)
        request_id = doc.get("request_id")
        if request_id is not None:
            if not isinstance(request_id, str) or not request_id:
                raise ValueError("request_id must be a non-empty string")
            if len(request_id) > 128:
                raise ValueError(
                    f"request_id is too long ({len(request_id)} chars; "
                    "max 128)"
                )
        priority = doc.get("priority", 1)
        if not isinstance(priority, int) or isinstance(priority, bool) \
                or not 0 <= priority <= 9:
            raise ValueError(
                f"priority must be an integer in [0, 9] (0 = most "
                f"urgent); got {priority!r}"
            )
        prefix_cache = doc.get("prefix_cache", True)
        if not isinstance(prefix_cache, bool):
            raise ValueError(
                f"prefix_cache must be a boolean; got {prefix_cache!r}"
            )
        speculate = doc.get("speculate", True)
        if not isinstance(speculate, bool):
            raise ValueError(
                f"speculate must be a boolean; got {speculate!r}"
            )
        prefill_only = doc.get("prefill_only", False)
        if not isinstance(prefill_only, bool):
            raise ValueError(
                f"prefill_only must be a boolean; got {prefill_only!r}"
            )
        trace_context = doc.get("trace_context")
        if trace_context is not None and (
                not isinstance(trace_context, str) or not trace_context):
            raise ValueError(
                "trace_context must be a non-empty string"
            )
        deadline = doc.get("deadline_s", self._default_deadline_s)
        # reject impossible shapes at submit time (400), not in the loop
        backend = self._scheduler.backend
        if hasattr(backend, "validate"):
            backend.validate(ids, max_new)
        return GenRequest(
            prompt=tuple(int(t) for t in ids),
            max_new_tokens=max_new,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            seed=int(doc.get("seed", 0)),
            stop_token=None if stop_token is None else int(stop_token),
            deadline_s=None if deadline is None else float(deadline),
            request_id=request_id,
            priority=priority,
            prefix_cache=prefix_cache,
            speculate=speculate,
            prefill_only=prefill_only,
            trace_context=trace_context,
        )

    def _request_spec(self, req: GenRequest, request_id: str) -> dict:
        """A GenRequest back in wire form — the ``request`` field of a
        shipped KV payload, so the importing replica rebuilds the EXACT
        sampling state through its own ``_parse_request`` validation.
        ``prefill_only`` deliberately does not travel: the import side
        resumes DECODE. ``deadline_s`` ships as the original relative
        budget — the decode replica restarts the window at import."""
        spec = {
            "token_ids": [int(t) for t in req.prompt],
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k),
            "top_p": float(req.top_p),
            "seed": int(req.seed),
            "request_id": request_id,
            "priority": int(req.priority),
            "prefix_cache": bool(req.prefix_cache),
            "speculate": bool(req.speculate),
        }
        if req.stop_token is not None:
            spec["stop_token"] = int(req.stop_token)
        else:
            # an explicit no-stop must survive the trip: without this,
            # the importer's default would re-attach its tokenizer EOS
            spec["stop"] = False
        if req.deadline_s is not None:
            spec["deadline_s"] = float(req.deadline_s)
        return spec

    # -- fleet control plane -------------------------------------------------

    def handle_admin(self, path: str, doc: dict) -> tuple[int, dict]:
        """The drain/resume/swap endpoints the fleet router drives
        (fleet/router.py) — a replica's side of a weight push."""
        sched = self._scheduler
        if path == "/admin/drain":
            sched.drain()
            return 200, {"draining": True, "in_flight": sched.in_flight()}
        if path == "/admin/resume":
            sched.resume()
            return 200, {"draining": False}
        if path == "/admin/admission":
            # class-aware shedding ceiling (fleet router / autoscaler):
            # {"max_priority": N} — classes above N are refused with the
            # shed 429 until raised again
            mp = doc.get("max_priority")
            try:
                return 200, {
                    "max_priority": sched.set_admission_max_priority(mp)
                }
            except (ValueError, AttributeError) as e:
                return 400, {"error": str(e)}
        if path == "/admin/kv/export":
            return self._handle_kv_export(doc)
        if path == "/admin/kv/import":
            return self._handle_kv_import(doc)
        # /admin/swap
        if self._swap_loader is None:
            return 404, {
                "error": "this server has no swap loader (the serve CLI "
                         "configures one; embedded servers pass "
                         "swap_loader=)"
            }
        backend = sched.backend
        if not hasattr(backend, "swap_weights"):
            return 404, {"error": "backend does not support weight swaps"}
        if not self.loop_alive():
            return 503, {"error": "engine loop is not running",
                         "detail": self._loop_error}
        ckpt = doc.get("checkpoint_dir")
        step = doc.get("step")
        if not isinstance(ckpt, str) or not ckpt:
            return 400, {"error": "checkpoint_dir must be a non-empty string"}
        if step is not None and (isinstance(step, bool)
                                 or not isinstance(step, int)):
            return 400, {"error": f"step must be an integer; got {step!r}"}
        try:
            # the LOAD runs on this HTTP thread (disk + host work); only
            # the swap itself crosses to the tick thread
            params = self._swap_loader(ckpt, step)
        except (ValueError, FileNotFoundError, KeyError, SystemExit) as e:
            return 400, {"error": f"cannot load checkpoint: {e}"}
        handle = sched.call_on_tick(lambda: backend.swap_weights(params))
        if not handle.wait(self._swap_timeout_s):
            return 504, {"error": "swap did not run within "
                                  f"{self._swap_timeout_s:.0f}s (tick "
                                  "loop wedged?)"}
        if handle.error:
            # swap_weights validates loudly (tree/shape mismatch) — the
            # checkpoint is the problem, not the server
            return 400, {"error": handle.error}
        return 200, {
            "swapped": True,
            "deploy_generation": handle.result,
            "checkpoint_dir": ckpt,
            **({"step": step} if step is not None else {}),
        }

    # -- KV shipping (disaggregated serving; fleet/disagg.py) ----------------

    def _handle_kv_export(self, doc: dict) -> tuple[int, dict]:
        """POST /admin/kv/export: ``{"request_id": str}`` — ship a
        PARKED prefilled stream's KV rows + resume cursor out and free
        its slot. 404 when nothing by that id is parked (expired past
        the park TTL, already exported, or never prefilled here)."""
        rid = doc.get("request_id")
        if not isinstance(rid, str) or not rid:
            return 400, {"error": "request_id must be a non-empty string"}
        if not self.loop_alive():
            return 503, {"error": "engine loop is not running",
                         "detail": self._loop_error}
        sched = self._scheduler
        # the router's export-leg trace context rides the export doc so
        # the scheduler's kv_export span joins the causal tree
        tctx = doc.get("trace_context")
        tctx = tctx if isinstance(tctx, str) and tctx else None
        handle = sched.call_on_tick(
            lambda: sched.export_parked(rid, trace_context=tctx)
        )
        if not handle.wait(self._swap_timeout_s):
            return 504, {"error": "export did not run within "
                                  f"{self._swap_timeout_s:.0f}s (tick "
                                  "loop wedged?)"}
        if handle.error:
            return 500, {"error": handle.error}
        if handle.result is None:
            return 404, {
                "error": f"no parked stream {rid!r} (expired, already "
                         "exported, or never prefilled here)"
            }
        raw, parked = handle.result
        shipped = kvship.ShippedKV(
            config=raw["config"],
            generation=raw["generation"],
            wire_dtype=raw["wire_dtype"],
            prompt_len=len(parked.request.prompt),
            pos=raw["pos"],
            step_idx=len(parked.tokens) - 1,
            emitted=list(parked.tokens),
            k=raw["k"], v=raw["v"],
            ks=raw.get("ks"), vs=raw.get("vs"),
            request=self._request_spec(parked.request, parked.request_id),
        )
        return 200, kvship.pack(shipped)

    def _handle_kv_import(self, doc: dict) -> tuple[int, dict]:
        """POST /admin/kv/import: body is a packed ship payload
        (``kvship.pack``) — map the shipped KV rows into this engine's
        own block pool and resume the stream mid-request. The answer IS
        the finished generate response (same shape as /v1/generate:
        the imported stream is in-flight here, cancellable by its id).
        400 malformed payload, 409 fingerprint mismatch (wrong config /
        weight generation), 429 no slot or KV blocks right now."""
        if not self.loop_alive():
            return 503, {"error": "engine loop is not running",
                         "detail": self._loop_error}
        try:
            shipped = kvship.unpack(doc)
        except kvship.ShipFormatError as e:
            return 400, {"error": str(e)}
        try:
            spec = dict(shipped.request)
            # the router's import-leg trace context arrives at the TOP
            # level of the packed payload (the spec itself is the
            # original request, minted before any handoff existed);
            # inject it so the decode-side spans parent under that leg
            tctx = doc.get("trace_context")
            if isinstance(tctx, str) and tctx and "trace_context" not in spec:
                spec["trace_context"] = tctx
            request = self._parse_request(spec)
        except (ValueError, TypeError) as e:
            return 400, {"error": f"bad shipped request spec: {e}"}
        sched = self._scheduler
        handle = sched.call_on_tick(
            lambda: sched.admit_import(request, shipped)
        )
        if not handle.wait(self._swap_timeout_s):
            return 504, {"error": "import did not run within "
                                  f"{self._swap_timeout_s:.0f}s (tick "
                                  "loop wedged?)"}
        if handle.error:
            # the tick thread serialized the raise as "Type: message";
            # map the type back onto the wire contract (409 = the
            # pairing is wrong and retrying THIS replica is pointless;
            # 429 = capacity, the router tries another decode replica)
            if handle.error.startswith("ShipMismatchError"):
                return 409, {"error": handle.error}
            if handle.error.startswith(("BlocksExhausted", "QueueFull")):
                return 429, {"error": handle.error}
            return 400, {"error": handle.error}
        return self._await_ticket(request, handle.result)

    # -- observability -------------------------------------------------------

    def health(self) -> tuple[int, dict]:
        s = self._scheduler.stats()
        alive = self.loop_alive()
        doc = {
            "healthy": alive,
            "queue_depth": s["queue_depth"],
            "slots_busy": s["slots_busy"],
            "slots_total": s["slots_total"],
            "served": s["served"],
            # the fleet router's routing inputs ride on the liveness
            # body (one GET per health tick, no /metrics parse): current
            # load, KV headroom, drain state, deploy generation, and the
            # disaggregated-serving tier this replica belongs to
            "draining": s.get("draining", False),
            "role": self.role,
        }
        kv = s.get("kv_pool")
        if isinstance(kv, dict) and kv.get("blocks_free") is not None:
            doc["kv_blocks_free"] = kv["blocks_free"]
        if s.get("deploy_generation") is not None:
            doc["deploy_generation"] = s["deploy_generation"]
        # total attributed device-seconds (all classes): the router's
        # per-replica cost gauge, riding the same one-GET probe
        dev = s.get("device_seconds_by_priority")
        if dev:
            doc["device_seconds_total"] = round(sum(dev.values()), 6)
        if self._loop_error:
            doc["error"] = self._loop_error
        return (200 if alive else 503), doc

    def readiness(self) -> tuple[int, dict]:
        """READINESS, split from liveness: can this replica take NEW
        traffic right now? A draining replica is alive (/healthz 200 —
        the router must not eject it as dead) but not ready (503 here)
        until its weight push resumes it."""
        alive = self.loop_alive()
        sched = self._scheduler
        draining = bool(getattr(sched, "draining", False))
        doc = {
            "ready": alive and not draining,
            "draining": draining,
            "in_flight": sched.in_flight(),
            "queue_depth": sched.queue_depth(),
        }
        gen = getattr(sched.backend, "deploy_generation", None)
        if gen is not None:
            doc["deploy_generation"] = int(gen)
        if self._loop_error:
            doc["error"] = self._loop_error
        return (200 if doc["ready"] else 503), doc

    def render_metrics(self) -> str:
        s = self._scheduler.stats()
        gauges = [
            ("nanodiloco_serve_queue_depth",
             "requests waiting for a slot", s["queue_depth"]),
            ("nanodiloco_serve_slots_busy",
             "slots with a live request (prefilling or decoding)",
             s["slots_busy"]),
            ("nanodiloco_serve_slots_prefilling",
             "slots mid-chunked-prefill", s.get("slots_prefilling")),
            ("nanodiloco_serve_slots_parked",
             "slots holding a prefilled stream awaiting KV export (the "
             "disaggregated handoff window)", s.get("slots_parked")),
            ("nanodiloco_serve_slots_total",
             "decode slots in the engine batch", s["slots_total"]),
            ("nanodiloco_serve_prefill_chunks_pending",
             "staged prefill chunks waiting for a tick interleave slot",
             s.get("prefill_chunks_pending")),
            ("nanodiloco_serve_ttft_seconds",
             "last request's time to first token", s["ttft_last_s"]),
            ("nanodiloco_serve_ttft_p50_seconds",
             "median TTFT over the last 512 admissions", s["ttft_p50_s"]),
            ("nanodiloco_serve_ttft_p95_seconds",
             "p95 TTFT over the last 512 admissions", s["ttft_p95_s"]),
            ("nanodiloco_serve_decode_tokens_per_sec",
             "aggregate decode throughput across live slots",
             s["decode_tokens_per_sec"]),
            ("nanodiloco_serve_tp_degree",
             "tensor-parallel shards the decode tick spans (1 = "
             "unsharded)", s.get("tp_degree")),
            ("nanodiloco_deploy_generation",
             "weight generation this replica serves (bumped by every "
             "hot swap; 0 = the boot checkpoint)",
             s.get("deploy_generation")),
            ("nanodiloco_serve_draining",
             "1 while admission is drained for a weight push (alive "
             "but not ready)", int(s["draining"]) if "draining" in s
             else None),
        ]
        families: list = [
            (name, "gauge", help_text, [(None, value)])
            for name, help_text, value in gauges
            if value is not None
        ]
        outcomes = s["requests_by_outcome"]
        families.append((
            "nanodiloco_serve_requests", "counter",
            "requests by terminal outcome",
            [({"outcome": k}, v) for k, v in outcomes.items()]
            + [(None, sum(outcomes.values()))],
        ))
        families.append((
            "nanodiloco_serve_tokens", "counter",
            "tokens sampled (prefill + decode)", [(None, s["tokens_out"])],
        ))
        families.append((
            "nanodiloco_serve_prefill_chunks", "counter",
            "prefill chunks run (one per tick interleave slot)",
            [(None, s.get("prefill_chunks_total", 0))],
        ))
        # disaggregated-serving tier + handoff traffic: the role gauge
        # (always present — the router's tier map), the abandoned-park
        # counter, and the KV ship meters (export/import split by the
        # direction label; present only once a ship has happened)
        families.append((
            "nanodiloco_serve_role", "gauge",
            "disaggregated-serving tier this replica declares (1 under "
            "its role label: prefill, decode, or both)",
            [({"role": self.role}, 1)],
        ))
        if s.get("park_expired") is not None:
            families.append((
                "nanodiloco_serve_park_expired", "counter",
                "parked prefilled slots reclaimed without export "
                "(abandoned disaggregated handoffs — TTL or deadline "
                "fired before /admin/kv/export)",
                [(None, s["park_expired"])],
            ))
        ship = s.get("kvship")
        if ship is not None:
            families.append((
                "nanodiloco_kv_ship_requests", "counter",
                "KV ship operations by direction (export = parked "
                "streams shipped out, import = shipped streams resumed "
                "here)",
                [({"direction": "export"}, ship["export_requests"]),
                 ({"direction": "import"}, ship["import_requests"])],
            ))
            families.append((
                "nanodiloco_kv_ship_bytes", "counter",
                "raw KV payload bytes shipped (pre-base64), by direction",
                [({"direction": "export"}, ship["export_bytes"]),
                 ({"direction": "import"}, ship["import_bytes"])],
            ))
            families.append((
                "nanodiloco_kv_ship_blocks", "counter",
                "KV cache blocks shipped (exporter's block geometry on "
                "export, importer's on import), by direction",
                [({"direction": "export"}, ship["export_blocks"]),
                 ({"direction": "import"}, ship["import_blocks"])],
            ))
            families.append((
                "nanodiloco_kv_ship_seconds", "counter",
                "host seconds spent gathering/scattering shipped KV, by "
                "direction",
                [({"direction": "export"}, ship["export_seconds"]),
                 ({"direction": "import"}, ship["import_seconds"])],
            ))
        if s.get("admission_blocked_no_slot") is not None:
            families.append((
                "nanodiloco_serve_admission_blocked", "counter",
                "ticks the next queued request could not be admitted, "
                "by cause (no_slot = slots exhausted, no_blocks = KV "
                "block pool exhausted)",
                [({"reason": "no_slot"}, s["admission_blocked_no_slot"]),
                 ({"reason": "no_blocks"},
                  s["admission_blocked_no_blocks"])],
            ))
        # paged KV block pool: the gauges that turn "how many more
        # requests fit this chip" from folklore into a scrape
        kv = s.get("kv_pool")
        if kv is not None:
            families.append((
                "nanodiloco_kv_blocks_free", "gauge",
                "KV cache blocks available for admission",
                [(None, kv["blocks_free"])],
            ))
            families.append((
                "nanodiloco_kv_blocks_used", "gauge",
                "KV cache blocks held by live slots and cached prefixes",
                [(None, kv["blocks_used"])],
            ))
            families.append((
                "nanodiloco_kv_block_evictions", "counter",
                "prefix-cache KV blocks dereferenced by LRU eviction",
                [(None, kv["block_evictions"])],
            ))
            families.append((
                "nanodiloco_kv_block_size_tokens", "gauge",
                "token rows per KV block", [(None, kv["block_size"])],
            ))
            per_shard = kv.get("blocks_free_per_shard")
            if per_shard:
                # its own family (not labeled samples on
                # nanodiloco_kv_blocks_free): a sum-by-family aggregation
                # over shard labels would multiply the global pool's
                # free count by tp — the prefix-cache lookup lesson
                families.append((
                    "nanodiloco_kv_blocks_free_per_shard", "gauge",
                    "KV blocks free per tensor-parallel shard (the host "
                    "pool is global: a block id names the same physical "
                    "block on every shard)",
                    [({"shard": str(sh)}, v)
                     for sh, v in sorted(per_shard.items())],
                ))
            hist = kv.get("hist_blocks_per_request")
            if hist is not None:
                families.append((
                    "nanodiloco_kv_blocks_per_request", "histogram",
                    "KV blocks a request held over its life (observed "
                    "at release)", hist,
                ))
        # speculative decoding: the draft/accept economics — the
        # acceptance-rate gauge is what says whether speculation is
        # earning its verify overhead on the live traffic mix
        spec = s.get("spec")
        if spec is not None:
            families.append((
                "nanodiloco_spec_draft_tokens", "counter",
                "draft tokens proposed by prompt-lookup speculation",
                [(None, spec["draft_tokens"])],
            ))
            families.append((
                "nanodiloco_spec_accepted", "counter",
                "draft tokens accepted by batched verification",
                [(None, spec["accepted_tokens"])],
            ))
            families.append((
                "nanodiloco_spec_rejected", "counter",
                "draft tokens rejected by batched verification",
                [(None, spec["rejected_tokens"])],
            ))
            if spec.get("acceptance_rate") is not None:
                families.append((
                    "nanodiloco_spec_acceptance_rate", "gauge",
                    "accepted / drafted over the engine's life",
                    [(None, spec["acceptance_rate"])],
                ))
            hist = spec.get("hist_tokens_per_tick")
            if hist is not None:
                families.append((
                    "nanodiloco_spec_tokens_per_tick", "histogram",
                    "tokens emitted per DRAFTING slot per speculative "
                    "tick (accepted prefix + the verified bonus token)",
                    hist,
                ))
        # shared-prefix KV cache: the counters that tell an operator
        # whether the system-prompt traffic is actually being reused
        pc = s.get("prefix_cache")
        if pc is not None:
            families.append((
                "nanodiloco_serve_prefix_cache_lookups", "counter",
                "prefix-cache lookups by result",
                [({"result": "hit"}, pc["hits"]),
                 ({"result": "miss"}, pc["misses"])],
            ))
            families.append((
                "nanodiloco_serve_prefix_cache_hit_tokens", "counter",
                "prompt tokens served from cached prefix K/V instead of "
                "prefill compute", [(None, pc["hit_tokens"])],
            ))
            families.append((
                "nanodiloco_serve_prefix_cache_insertions", "counter",
                "prefix chunks admitted to the cache",
                [(None, pc["insertions"])],
            ))
            families.append((
                "nanodiloco_serve_prefix_cache_evictions", "counter",
                "prefix chunks LRU-evicted", [(None, pc["evictions"])],
            ))
            families.append((
                "nanodiloco_serve_prefix_cache_tokens", "gauge",
                "tokens currently held in cached prefix chunks",
                [(None, pc["cached_tokens"])],
            ))
        # real distributions (cumulative buckets + _count/_sum): what a
        # scraper can alert and aggregate on, unlike the window gauges
        for name, help_text, key in (
            ("nanodiloco_serve_ttft_histogram_seconds",
             "time to first token, submit to first sampled token",
             "hist_ttft"),
            ("nanodiloco_serve_queue_wait_seconds",
             "slot wait, submit to admission", "hist_queue_wait"),
            ("nanodiloco_serve_decode_tick_seconds",
             "one compiled decode step advancing all live slots",
             "hist_decode_tick"),
        ):
            families.append((name, "histogram", help_text, s[key]))
        by_prio = s.get("hist_queue_wait_by_priority") or {}
        if by_prio:
            families.append((
                "nanodiloco_serve_queue_wait_by_priority_seconds",
                "histogram",
                "slot wait split by SLO priority class (0 = most urgent)",
                [({"priority": str(p)}, snap)
                 for p, snap in by_prio.items()],
            ))
        # class-aware overload shedding: the admission ceiling, the
        # per-class shed counts, and the per-class TTFT p95 — together
        # the honest story of WHO is being sacrificed under overload
        # and whether the protected class's latency actually held
        if s.get("admission_max_priority") is not None:
            families.append((
                "nanodiloco_serve_admission_max_priority", "gauge",
                "highest priority class currently admitted (9 = all; "
                "lower = overload shedding active)",
                [(None, s["admission_max_priority"])],
            ))
        shed = s.get("shed_by_priority") or {}
        if shed:
            families.append((
                "nanodiloco_serve_shed", "counter",
                "requests refused by class-aware overload shedding, by "
                "priority class",
                [({"priority": str(p)}, n)
                 for p, n in sorted(shed.items())]
                + [(None, sum(shed.values()))],
            ))
        ttft_by_prio = s.get("ttft_p95_by_priority") or {}
        if ttft_by_prio:
            families.append((
                "nanodiloco_serve_class_ttft_p95_seconds", "gauge",
                "p95 TTFT split by SLO priority class (0 = most urgent "
                "— the class whose SLO must hold while lower classes "
                "shed)",
                [({"priority": str(p)}, v)
                 for p, v in sorted(ttft_by_prio.items())
                 if v is not None],
            ))
        # per-class cost metering: device-seconds consumed and KV
        # block-seconds held, rolled up from per-request attribution —
        # the billing counters for the millions-of-users story
        dev_by_prio = s.get("device_seconds_by_priority") or {}
        if dev_by_prio:
            families.append((
                "nanodiloco_serve_device_seconds", "counter",
                "attributed dispatch seconds (prefill + decode) by SLO "
                "priority class, summed over finished requests",
                [({"priority": str(p)}, v)
                 for p, v in sorted(dev_by_prio.items())]
                + [(None, round(sum(dev_by_prio.values()), 6))],
            ))
        kvbs_by_prio = s.get("kv_block_seconds_by_priority") or {}
        if kvbs_by_prio:
            families.append((
                "nanodiloco_serve_kv_block_seconds", "counter",
                "KV block-seconds held (blocks x residency time) by SLO "
                "priority class, settled at release",
                [({"priority": str(p)}, v)
                 for p, v in sorted(kvbs_by_prio.items())]
                + [(None, round(sum(kvbs_by_prio.values()), 6))],
            ))
        # decode-tick interference: the DistServe tier-split signal —
        # p50 decode tick with vs without staged prefill chunks pending
        if s.get("decode_interference_ratio") is not None:
            families.append((
                "nanodiloco_serve_decode_interference_ratio", "gauge",
                "p50 decode tick time with pending prefill chunks / p50 "
                "without (>1 = prefill interleave is stretching decode "
                "ticks; the prefill/decode tier-split sizing signal)",
                [(None, s["decode_interference_ratio"])],
            ))
        # per-program dispatch ledgers from the engine's accountant —
        # one family definition (obs/devtime) shared with the trainer's
        # telemetry endpoint so the exposition cannot drift
        families.extend(devtime_families(s.get("devtime")))
        return render_exposition(families)
