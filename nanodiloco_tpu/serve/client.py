"""Tiny stdlib client for the serving endpoints — the ONE place the
wire contract (JSON bodies, HTTPError-carries-the-response) is encoded,
shared by tests, ``scripts/serve_bench.py``, and ``chip_agenda.py``'s
serve phase so they cannot drift from each other."""

from __future__ import annotations

import json
import urllib.error
import urllib.request


def http_get(url: str, timeout: float = 10.0) -> tuple[int, str]:
    """GET -> (status, body text). A 4xx/5xx IS the response (healthz
    503 is the most interesting thing a probe can read), never raised."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def http_post_json(url: str, doc: dict,
                   timeout: float = 600.0) -> tuple[int, dict]:
    """POST a JSON object -> (status, parsed JSON response)."""
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())
