"""KV block shipping: the wire format for moving a live request's
paged (or dense) KV cache between serving replicas.

This is the mechanism behind disaggregated prefill/decode serving
(DistServe, arXiv:2401.09670; Splitwise, arXiv:2311.18677): a prefill
replica computes a prompt's KV rows and its first sampled token, then
ships the rows to a decode replica which resumes the stream mid-request
— exactly like a prefix-cache hit crossing a process boundary. The
format is deliberately LAYOUT-INVARIANT: rows travel as
``[layers, tokens, kv_heads, head_dim]`` regardless of the exporter's
block size, pool size, or tensor-parallel degree (the host block pool
is global under TP — a block id names the same physical block on every
shard — so a tp=4 exporter and a tp=1 importer exchange identical
bytes). The importer re-blocks into its OWN pool geometry.

Dtype rules (the parity contract):

- an int8 arena ships its stored int8 rows + per-row f32 scales
  verbatim; an int8 importer stores them verbatim — bit-exact, the
  same bits attention would have read locally;
- an fp arena ships raw fp bits; a same-dtype fp importer stores them
  verbatim — bit-exact, so a disaggregated stream is bit-identical to
  solo ``generate()``;
- cross-dtype imports requantize (fp wire -> int8 arena via the proven
  amax/127 scheme) or dequantize (int8 wire -> fp arena), trading
  bit-parity for compatibility the same way the int8 arena itself
  does; an fp wire into a DIFFERENT fp arena dtype is refused loudly
  (``ShipMismatchError``) — silently casting bf16 bits into an f32
  arena would be the quiet-garbage failure this module exists to
  prevent.

Every payload carries a FINGERPRINT — config hash + weight deploy
generation + wire dtype — validated before a single row lands: a
mismatched architecture or weight generation is a loud 4xx on the
import path (``ShipMismatchError`` -> 409), a truncated or malformed
payload a ``ShipFormatError`` (-> 400), never silent garbage in the
decode replica's cache.

Stdlib + numpy only; the engine owns the device work
(``InferenceEngine.export_kv`` / ``import_kv``).
"""

from __future__ import annotations

import base64
import binascii
import dataclasses
import hashlib
import json

import numpy as np

__all__ = [
    "SHIP_VERSION",
    "ShipFormatError",
    "ShipMismatchError",
    "ShippedKV",
    "config_fingerprint",
    "pack",
    "unpack",
    "quantize_rows",
    "dequantize_rows",
]

SHIP_VERSION = 1


class ShipFormatError(ValueError):
    """Malformed payload: bad base64, truncated buffer, inconsistent
    cursor, missing field. The importing server answers 400 — the
    sender's bytes are broken, retrying them is pointless."""


class ShipMismatchError(ValueError):
    """Well-formed payload that does not fit THIS engine: wrong config
    fingerprint (different architecture), wrong weight generation, or
    an fp wire dtype the arena cannot hold bit-exactly. The importing
    server answers 409 — the payload is fine, the pairing is not."""


def config_fingerprint(cfg) -> str:
    """Stable 16-hex digest of the model config: the architecture half
    of the ship fingerprint. Two engines agree iff their configs are
    field-for-field identical — shipping KV across architectures would
    be silent garbage, and this makes it a loud 409 instead."""
    doc = json.dumps(
        dataclasses.asdict(cfg), sort_keys=True, default=str
    )
    return hashlib.sha256(doc.encode()).hexdigest()[:16]


def quantize_rows(rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-token-row symmetric int8 quantization — the HOST twin of the
    engine arena's ``_quantize_rows`` (models/generate.py): amax over
    the (kv_heads, head_dim) axes, ``scale = max(amax, 1e-8) / 127``.
    ``rows`` is ``[..., T, H, hd]``; returns (int8 rows, f32 scales
    ``[..., T]``)."""
    f = np.asarray(rows, np.float32)
    amax = np.max(np.abs(f), axis=(-2, -1))
    scale = (np.maximum(amax, 1e-8) / 127.0).astype(np.float32)
    q = np.clip(np.rint(f / scale[..., None, None]), -127, 127)
    return q.astype(np.int8), scale


def dequantize_rows(q: np.ndarray, scale: np.ndarray,
                    dtype) -> np.ndarray:
    """Inverse of ``quantize_rows`` into ``dtype`` — the same math the
    paged-int8 attention read performs on device."""
    return (
        np.asarray(q, np.float32) * np.asarray(scale, np.float32)[..., None, None]
    ).astype(dtype)


@dataclasses.dataclass
class ShippedKV:
    """One request's shipped cache + resume cursor, decoded form.

    ``k``/``v`` are ``[layers, pos, kv_heads, head_dim]`` in
    ``wire_dtype`` (``ks``/``vs`` the ``[layers, pos]`` f32 scales,
    int8 wire only). ``emitted`` are the tokens the stream already
    produced (>= 1: the prefill's first sample rides along —
    ``pos == prompt_len + len(emitted) - 1`` because the newest token's
    own KV row is written by the tick that consumes it, not the one
    that sampled it). ``request`` is the originating generate-request
    spec, so an importer can rebuild the exact sampling state (the PRNG
    schedule is seed-derived — no key material travels)."""

    config: str
    generation: int
    wire_dtype: str
    prompt_len: int
    pos: int
    step_idx: int
    emitted: list[int]
    k: np.ndarray
    v: np.ndarray
    ks: np.ndarray | None
    vs: np.ndarray | None
    request: dict

    def payload_bytes(self) -> int:
        """Raw (pre-base64) KV payload size — the ship-bytes meter."""
        n = self.k.nbytes + self.v.nbytes
        if self.ks is not None:
            n += self.ks.nbytes
        if self.vs is not None:
            n += self.vs.nbytes
        return int(n)


def _np_dtype(name: str) -> np.dtype:
    """Wire dtype tag -> numpy dtype; covers jax's ml_dtypes extras
    (bfloat16) that plain ``np.dtype`` cannot name."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError, TypeError):
        raise ShipFormatError(f"unknown wire dtype {name!r}") from None


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode()

def _unb64(field: str, data, dtype: np.dtype, shape: tuple) -> np.ndarray:
    if not isinstance(data, str):
        raise ShipFormatError(f"field {field!r} must be a base64 string")
    try:
        raw = base64.b64decode(data.encode(), validate=True)
    except (binascii.Error, ValueError) as e:
        raise ShipFormatError(f"field {field!r}: bad base64 ({e})") from None
    want = int(np.prod(shape)) * dtype.itemsize
    if len(raw) != want:
        raise ShipFormatError(
            f"field {field!r}: payload is {len(raw)} bytes but the "
            f"declared shape {tuple(shape)} x {dtype} needs {want} — "
            "truncated or corrupt ship"
        )
    return np.frombuffer(raw, dtype).reshape(shape).copy()


def _int(doc: dict, field: str, minimum: int = 0) -> int:
    v = doc.get(field)
    if not isinstance(v, int) or isinstance(v, bool) or v < minimum:
        raise ShipFormatError(
            f"field {field!r} must be an integer >= {minimum}; got {v!r}"
        )
    return v


def pack(shipped: ShippedKV) -> dict:
    """ShippedKV -> JSON-safe wire doc (arrays base64-encoded)."""
    doc = {
        "version": SHIP_VERSION,
        "config": shipped.config,
        "generation": int(shipped.generation),
        "wire_dtype": shipped.wire_dtype,
        "prompt_len": int(shipped.prompt_len),
        "pos": int(shipped.pos),
        "step_idx": int(shipped.step_idx),
        "emitted": [int(t) for t in shipped.emitted],
        "layers": int(shipped.k.shape[0]),
        "kv_heads": int(shipped.k.shape[2]),
        "head_dim": int(shipped.k.shape[3]),
        "k": _b64(shipped.k),
        "v": _b64(shipped.v),
        "request": dict(shipped.request),
    }
    if shipped.ks is not None:
        doc["ks"] = _b64(np.asarray(shipped.ks, np.float32))
        doc["vs"] = _b64(np.asarray(shipped.vs, np.float32))
    return doc


def unpack(doc: dict) -> ShippedKV:
    """Wire doc -> ShippedKV, validating EVERYTHING structural here so
    the engine's import sees only well-formed payloads: version, field
    types, base64 integrity, buffer-length-vs-shape agreement, and the
    cursor identities (``pos == prompt_len + len(emitted) - 1``,
    ``step_idx == len(emitted) - 1``). Fingerprint/generation checks
    are the ENGINE's (it knows its config) — format first, fit second."""
    if not isinstance(doc, dict):
        raise ShipFormatError("ship payload must be a JSON object")
    version = doc.get("version")
    if version != SHIP_VERSION:
        raise ShipFormatError(
            f"unsupported ship version {version!r} (this build speaks "
            f"{SHIP_VERSION})"
        )
    config = doc.get("config")
    if not isinstance(config, str) or not config:
        raise ShipFormatError("field 'config' must be a non-empty string")
    wire = doc.get("wire_dtype")
    if not isinstance(wire, str) or not wire:
        raise ShipFormatError("field 'wire_dtype' must be a non-empty string")
    dtype = _np_dtype(wire)
    generation = _int(doc, "generation")
    prompt_len = _int(doc, "prompt_len", minimum=1)
    pos = _int(doc, "pos", minimum=1)
    step_idx = _int(doc, "step_idx")
    layers = _int(doc, "layers", minimum=1)
    kv_heads = _int(doc, "kv_heads", minimum=1)
    head_dim = _int(doc, "head_dim", minimum=1)
    emitted = doc.get("emitted")
    if (not isinstance(emitted, list) or not emitted
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       for t in emitted)):
        raise ShipFormatError(
            "field 'emitted' must be a non-empty list of ints (a "
            "shipped stream has sampled at least its first token)"
        )
    if pos != prompt_len + len(emitted) - 1:
        raise ShipFormatError(
            f"cursor mismatch: pos={pos} but prompt_len={prompt_len} + "
            f"{len(emitted)} emitted tokens implies "
            f"{prompt_len + len(emitted) - 1} written KV rows"
        )
    if step_idx != len(emitted) - 1:
        raise ShipFormatError(
            f"cursor mismatch: step_idx={step_idx} but {len(emitted)} "
            f"emitted tokens implies {len(emitted) - 1} decode steps"
        )
    request = doc.get("request")
    if not isinstance(request, dict):
        raise ShipFormatError("field 'request' must be a JSON object")
    shape = (layers, pos, kv_heads, head_dim)
    k = _unb64("k", doc.get("k"), dtype, shape)
    v = _unb64("v", doc.get("v"), dtype, shape)
    ks = vs = None
    if dtype == np.dtype(np.int8):
        ks = _unb64("ks", doc.get("ks"), np.dtype(np.float32),
                    (layers, pos))
        vs = _unb64("vs", doc.get("vs"), np.dtype(np.float32),
                    (layers, pos))
    elif "ks" in doc or "vs" in doc:
        raise ShipFormatError(
            "scale fields ('ks'/'vs') only belong on int8 wire payloads"
        )
    return ShippedKV(
        config=config, generation=generation, wire_dtype=wire,
        prompt_len=prompt_len, pos=pos, step_idx=step_idx,
        emitted=[int(t) for t in emitted], k=k, v=v, ks=ks, vs=vs,
        request=dict(request),
    )
