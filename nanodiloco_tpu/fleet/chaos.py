"""Fleet chaos harness: schedule-driven wire faults in front of real replicas.

``resilience/faults.py`` proved every TRAINING recovery path by driving
failures through the real stack at exact, reproducible steps. The fleet
grew the same way training did — router, canary deploy, autoscaler,
collector — but its failure paths were hardened only by hand-found
review fixes. This module closes that gap for SERVING: a deterministic
fault plan (keyed by per-target request/probe ordinals — zero
wall-clock randomness, identical on every run with the same plan)
realized by a stdlib ``ChaosProxy`` that sits ON THE WIRE in front of a
real replica, so every fault is observed exactly as production would
see it — through sockets, not through monkeypatched Python.

The plan is a JSON document (``fleet --chaos-plan plan.json``)::

    {"faults": [
      {"kind": "latency",     "target": "r0", "requests": [2], "seconds": 0.5},
      {"kind": "slow_drip",   "target": "r1", "requests": [4], "seconds": 0.5},
      {"kind": "reset",       "target": "r2", "requests": [5]},
      {"kind": "blackhole",   "target": "r0", "requests": [6], "seconds": 8},
      {"kind": "error_500",   "target": "r1", "requests": [7, 8, 9]},
      {"kind": "garbage_json","target": "r0", "requests": [10]},
      {"kind": "flap_health", "target": "r2", "probes": [3]},
      {"kind": "kill",        "target": "r2", "requests": [11]}
    ]}

Fault kinds (the gray-failure taxonomy the router's resilience stack —
deadline propagation, hedging, retry budgets, circuit breakers — must
survive):

- ``latency``: hold the request ``seconds`` before forwarding — the
  slow-but-200 replica binary healthz cannot see (hedge territory).
- ``slow_drip``: forward normally, then dribble the response body out
  in ``chunk_bytes`` pieces spread over ``seconds`` — a slow byte
  stream, not a slow first byte.
- ``reset``: forward, write a PARTIAL body, then abort the connection
  with an RST (``SO_LINGER`` 0) — the classic mid-response connection
  reset; the stream must be retried, never dropped.
- ``blackhole``: read the request, then hold the socket up to
  ``seconds`` and close WITHOUT replying — accept-and-never-answer,
  deadline propagation's worst case.
- ``error_500``: answer 500 with a JSON error body, upstream untouched.
- ``garbage_json``: answer 200 with a body that is not JSON — the
  intermediary error page / corrupted response case.
- ``flap_health``: answer the listed ``/healthz`` PROBE ordinals 503 —
  a flapping health endpoint must cost a tick of readiness, not an
  ejection.
- ``kill``: invoke the harness's ``on_kill(target)`` callback (which
  kills the real replica process) and abort the triggering connection —
  a hard replica death WITH streams in flight. Without a callback (the
  CLI fronting external replicas it does not own) the fault is
  record-only plus the abort.

Ordinals count per target per channel: ``requests`` index the
``POST /v1/generate`` calls THIS proxy has seen (0-based), ``probes``
index its ``GET /healthz`` calls. Every other path (``/admin/*``,
``/v1/cancel``, ``/readyz``, ``/metrics``) forwards untouched and
consumes no ordinal — a cancel must never eat a scheduled fault.

Hook contract mirrors ``FaultPlan``: each (fault, ordinal) pair fires
exactly once, fired records accumulate for ``drain_fired()`` (the
``{"chaos": kind, ...}`` JSONL timeline ``summarize_run`` reads), and
``counts()`` feeds the ``nanodiloco_chaos_injected`` counter family.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import urlsplit

KINDS = (
    "latency", "slow_drip", "reset", "blackhole", "error_500",
    "garbage_json", "flap_health", "kill",
)

#: kinds keyed by /healthz probe ordinals; everything else keys on
#: /v1/generate request ordinals
PROBE_KINDS = ("flap_health",)


class ChaosPlan:
    """Parsed, validated chaos schedule with firing bookkeeping.

    Thread-safe: one plan is shared by every proxy in a drill (each
    proxy's handler threads consult it concurrently), and the per-
    target per-channel ordinal is supplied by the proxy — the plan
    itself holds no clocks and no randomness."""

    def __init__(self, faults: list[dict[str, Any]]) -> None:
        self._lock = threading.Lock()
        self.fired: list[dict[str, Any]] = []   # records, in firing order
        self._counts: dict[str, int] = {}
        self.faults = []
        for i, f in enumerate(faults):
            if not isinstance(f, dict):
                raise ValueError(f"chaos fault #{i} is not an object: {f!r}")
            kind = f.get("kind")
            if kind not in KINDS:
                raise ValueError(
                    f"chaos fault #{i} has unknown kind {kind!r}; use one "
                    f"of {KINDS}"
                )
            if not isinstance(f.get("target"), str) or not f["target"]:
                raise ValueError(
                    f"chaos fault #{i} ({kind}) needs a non-empty target "
                    f"replica name; got {f.get('target')!r}"
                )
            f = dict(f)
            key = "probes" if kind in PROBE_KINDS else "requests"
            other = "requests" if key == "probes" else "probes"
            if f.get(other) is not None:
                raise ValueError(
                    f"chaos fault #{i} ({kind}) keys on {key!r}, not "
                    f"{other!r}"
                )
            ords = f.get(key)
            if not (isinstance(ords, list) and ords and all(
                isinstance(o, int) and not isinstance(o, bool) and o >= 0
                for o in ords
            )):
                raise ValueError(
                    f"chaos fault #{i} ({kind}) needs {key!r}: a non-empty "
                    f"list of integer ordinals >= 0; got {ords!r}"
                )
            f[key] = sorted(set(ords))
            if kind in ("latency", "slow_drip"):
                f["seconds"] = float(f.get("seconds", 0.5))
                if f["seconds"] <= 0:
                    raise ValueError(
                        f"{kind} fault #{i} seconds must be > 0"
                    )
            if kind == "slow_drip":
                f["chunk_bytes"] = int(f.get("chunk_bytes", 64))
                if f["chunk_bytes"] < 1:
                    raise ValueError(
                        f"slow_drip fault #{i} chunk_bytes must be >= 1"
                    )
            if kind == "blackhole":
                f["seconds"] = float(f.get("seconds", 30.0))
                if f["seconds"] <= 0:
                    raise ValueError(
                        f"blackhole fault #{i} seconds must be > 0"
                    )
            f["_idx"] = i
            f["_fired"] = set()   # ordinals already fired
            self.faults.append(f)

    @classmethod
    def from_dict(cls, doc: dict[str, Any]) -> "ChaosPlan":
        faults = doc.get("faults")
        if not isinstance(faults, list):
            raise ValueError(
                'chaos plan must be {"faults": [...]} with a list of fault '
                f"objects; got {type(faults).__name__}"
            )
        return cls(faults)

    @classmethod
    def load(cls, path: str) -> "ChaosPlan":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def take(self, channel: str, target: str,
             ordinal: int) -> list[dict[str, Any]]:
        """Due, unfired faults for this (channel, target, ordinal) —
        marked fired and recorded. ``channel`` is ``"request"`` or
        ``"probe"``; each (fault, ordinal) pair fires exactly once."""
        key = "probes" if channel == "probe" else "requests"
        out = []
        with self._lock:
            for f in self.faults:
                if (f["target"] == target and f.get(key)
                        and ordinal in f[key]
                        and ordinal not in f["_fired"]):
                    f["_fired"].add(ordinal)
                    kind = f["kind"]
                    self._counts[kind] = self._counts.get(kind, 0) + 1
                    self.fired.append({
                        "chaos": kind, "target": target, "ordinal": ordinal,
                        **{k: v for k, v in f.items()
                           if not k.startswith("_")
                           and k not in ("kind", "target", key)},
                    })
                    out.append(f)
        return out

    def counts(self) -> dict[str, int]:
        """Injections by kind so far — the chaos counter family's data."""
        with self._lock:
            return dict(sorted(self._counts.items()))

    def drain_fired(self) -> list[dict[str, Any]]:
        """Fired records since the last drain — the harness logs each as
        a ``{"chaos": kind, ...}`` JSONL record, the fault-timeline
        shape ``summarize_run`` reads."""
        with self._lock:
            out, self.fired = self.fired, []
        return out


def chaos_families(counts: dict[str, int]) -> list:
    """The chaos injection counter family for ``render_exposition`` —
    one family, labeled by fault kind, embedded by whoever owns the
    drill's exposition (the proxy's ``/chaos/status`` carries the same
    numbers as JSON)."""
    if not counts:
        return []
    return [(
        "nanodiloco_chaos_injected", "counter",
        "wire faults injected by the chaos proxy, by kind (schedule-"
        "driven, per-target request/probe ordinals — deterministic)",
        [({"kind": k}, v) for k, v in sorted(counts.items())]
        + [(None, sum(counts.values()))],
    )]


class ChaosProxy:
    """A stdlib HTTP proxy fronting ONE replica, realizing the plan's
    faults for its ``target`` name. Start with ``start()``; the fleet
    router is pointed at ``url`` instead of the replica's own address,
    so every fault arrives through a real socket.

    ``on_kill(target)`` is the harness's replica-killer (SIGKILL a
    serve subprocess, ``stop()`` an in-process server); ``None`` makes
    ``kill`` faults record-only plus the connection abort."""

    def __init__(self, upstream_url: str, plan: ChaosPlan, target: str, *,
                 host: str = "127.0.0.1", port: int = 0,
                 on_kill: Callable[[str], None] | None = None) -> None:
        sp = urlsplit(upstream_url)
        if not sp.hostname or not sp.port:
            raise ValueError(
                f"upstream_url must be http://host:port; got {upstream_url!r}"
            )
        self.upstream_host = sp.hostname
        self.upstream_port = int(sp.port)
        self.plan = plan
        self.target = target
        self.on_kill = on_kill
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._request_ordinal = 0
        self._probe_ordinal = 0
        self._thread: threading.Thread | None = None

        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_GET(self):
                proxy._handle(self, b"")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                proxy._handle(self, self.rfile.read(n) if n else b"")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self.url = f"http://{host}:{self.port}"

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ChaosProxy":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"nanodiloco-chaos-{self.target}", daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._thread = None

    # -- the wire ----------------------------------------------------------

    def _ordinal(self, channel: str) -> int:
        with self._lock:
            if channel == "probe":
                n = self._probe_ordinal
                self._probe_ordinal += 1
            else:
                n = self._request_ordinal
                self._request_ordinal += 1
        return n

    def _handle(self, h: BaseHTTPRequestHandler, body: bytes) -> None:
        path = h.path.split("?", 1)[0]
        if path == "/chaos/status":
            self._reply_json(h, 200, {
                "target": self.target,
                "counts": self.plan.counts(),
            })
            return
        faults: list[dict] = []
        if h.command == "POST" and path == "/v1/generate":
            faults = self.plan.take("request", self.target,
                                    self._ordinal("request"))
        elif h.command == "GET" and path == "/healthz":
            faults = self.plan.take("probe", self.target,
                                    self._ordinal("probe"))
        by_kind = {f["kind"]: f for f in faults}

        if "flap_health" in by_kind:
            self._reply_json(h, 503, {"alive": False, "chaos": "flap_health"})
            return
        if "error_500" in by_kind:
            self._reply_json(h, 500, {"error": "chaos injected 500"})
            return
        if "garbage_json" in by_kind:
            raw = b"<html>502 bad gateway (chaos)</html>"
            h.send_response(200)
            h.send_header("Content-Type", "application/json")
            h.send_header("Content-Length", str(len(raw)))
            h.end_headers()
            h.wfile.write(raw)
            return
        if "blackhole" in by_kind:
            # accept, read, never answer: hold the socket (bounded, so a
            # stopping drill does not leak the handler thread), then
            # close without a byte — the caller's timeout is the only
            # way out
            self._stop.wait(by_kind["blackhole"]["seconds"])
            self._abort(h)
            return
        if "kill" in by_kind:
            if self.on_kill is not None:
                try:
                    self.on_kill(self.target)
                except Exception:
                    pass  # the drill's killer failing must not also
                    # kill the proxy's handler thread
            self._abort(h)
            return
        if "latency" in by_kind:
            # request-path latency: the replica sees the request late,
            # the client sees the answer late — the slow-but-200 shape
            self._stop.wait(by_kind["latency"]["seconds"])

        code, headers, payload = self._forward(h.command, path, body)
        if code is None:
            # upstream dead (a killed replica behind a still-living
            # proxy): surface it as the wire would — an aborted
            # connection, not a synthesized status the router might
            # misread as the replica's own answer
            self._abort(h)
            return

        if "reset" in by_kind and payload:
            h.send_response(code)
            for k, v in headers:
                h.send_header(k, v)
            h.end_headers()
            try:
                h.wfile.write(payload[: max(1, len(payload) // 2)])
                h.wfile.flush()
            except OSError:
                pass
            self._abort(h)
            return

        h.send_response(code)
        for k, v in headers:
            h.send_header(k, v)
        h.end_headers()
        try:
            if "slow_drip" in by_kind and payload:
                f = by_kind["slow_drip"]
                chunks = [payload[i:i + f["chunk_bytes"]]
                          for i in range(0, len(payload), f["chunk_bytes"])]
                pause = f["seconds"] / max(1, len(chunks))
                for c in chunks:
                    h.wfile.write(c)
                    h.wfile.flush()
                    self._stop.wait(pause)
            elif payload:
                h.wfile.write(payload)
        except OSError:
            pass  # client gone mid-body: its problem, not the proxy's

    def _forward(self, method: str, path: str,
                 body: bytes) -> tuple[int | None, list, bytes]:
        try:
            conn = HTTPConnection(self.upstream_host, self.upstream_port,
                                  timeout=600.0)
            hdrs = {"Content-Type": "application/json"} if body else {}
            conn.request(method, path, body=body or None, headers=hdrs)
            r = conn.getresponse()
            payload = r.read()
            headers = [(k, v) for k, v in r.getheaders()
                       if k.lower() in ("content-type", "content-length")]
            if not any(k.lower() == "content-length" for k, _ in headers):
                headers.append(("Content-Length", str(len(payload))))
            conn.close()
            return r.status, headers, payload
        except OSError:
            return None, [], b""

    def _reply_json(self, h: BaseHTTPRequestHandler, code: int,
                    doc: dict) -> None:
        raw = (json.dumps(doc) + "\n").encode()
        h.send_response(code)
        h.send_header("Content-Type", "application/json")
        h.send_header("Content-Length", str(len(raw)))
        h.end_headers()
        h.wfile.write(raw)

    def _abort(self, h: BaseHTTPRequestHandler) -> None:
        """Drop the connection with an RST (SO_LINGER 0): the peer sees
        a connection reset, not a polite FIN it could mistake for a
        complete short response."""
        try:
            h.connection.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0),
            )
        except OSError:
            pass
        try:
            h.connection.close()
        except OSError:
            pass
        h.close_connection = True


#: The committed drill every harness runs (``serve_bench --workload
#: chaos`` and ``chip_agenda.py chaos``): one fault of every kind
#: against a 3-replica fleet — slow-but-200 latency and a drip on two
#: replicas, a mid-response reset, a 500 burst long enough to trip r1's
#: circuit breaker, a garbage body, one flapped healthz probe (must NOT
#: eject), a blackhole (deadline propagation's worst case), and a hard
#: kill of r2 with streams in flight. Ordinals are per-target request
#: counts, so the drill is schedule-driven regardless of which client
#: request lands where.
DRILL_PLAN = {"faults": [
    {"kind": "latency", "target": "r0", "requests": [1], "seconds": 1.0},
    {"kind": "slow_drip", "target": "r1", "requests": [2],
     "seconds": 0.4, "chunk_bytes": 48},
    {"kind": "reset", "target": "r2", "requests": [2]},
    {"kind": "error_500", "target": "r1", "requests": [3, 4, 5]},
    {"kind": "garbage_json", "target": "r0", "requests": [4]},
    {"kind": "flap_health", "target": "r2", "probes": [2]},
    {"kind": "blackhole", "target": "r0", "requests": [6], "seconds": 8.0},
    {"kind": "kill", "target": "r2", "requests": [5]},
]}


def proxy_fleet(replicas, plan: ChaosPlan, *,
                host: str = "127.0.0.1",
                on_kill: Callable[[str], None] | None = None):
    """Front each ``Replica`` with a started ``ChaosProxy`` and return
    ``(proxied_replicas, proxies)`` — the proxied list carries the SAME
    names and blackbox paths with proxy URLs, so the router's view of
    the fleet is unchanged except that every byte now crosses the
    chaos wire. Callers own ``stop()`` on the returned proxies."""
    import dataclasses

    proxies = []
    proxied = []
    for r in replicas:
        p = ChaosProxy(r.url, plan, r.name, host=host,
                       on_kill=on_kill).start()
        proxies.append(p)
        proxied.append(dataclasses.replace(r, url=p.url))
    return proxied, proxies


# noqa convenience: time is used by nothing above on purpose — every
# delay is a stop-event wait so a stopping drill never hangs teardown
_ = time
