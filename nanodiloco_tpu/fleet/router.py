"""Fleet router: N serve replicas behind one front door.

The serving tier (nanodiloco_tpu/serve) is one replica: one engine, one
scheduler, one HTTP endpoint. This module is the fleet layer above it —
the piece that turns "a server" into "a service" (ROADMAP item 1, the
millions-of-users scenario; MegaScale's every-second-accounted
discipline, arXiv:2402.15627, applied to serving):

- **Load spreading.** ``POST /v1/generate`` forwards each request to the
  least-loaded READY replica, scored from the gauges the replicas
  already expose on their health bodies: queue depth + busy slots
  first, then most free KV blocks (HBM headroom breaks ties — two
  replicas with equal queues differ in how many more admissions their
  block pools can take). A local in-flight counter per replica keeps
  the spread honest BETWEEN health ticks.
- **Ejection.** A health loop probes every replica's ``/healthz``
  (liveness) and ``/readyz`` (readiness). An explicit 503 on /healthz
  means the engine loop DIED — that replica never recovers and is
  ejected immediately; an unreachable socket is ejected after
  ``eject_after_failures`` consecutive probes (a restart window is not
  a death). The ejection event attaches the replica's flight-recorder
  black box (``serve --blackbox`` dump) when one exists: the forensics
  travel WITH the fleet event, not in a log directory someone has to
  know about.
- **Drain/refill weight pushes.** ``push_weights`` walks the target
  replicas ONE AT A TIME: drain (the replica flips not-ready and stops
  admitting; the router stops routing to it), wait — bounded — for
  in-flight streams to finish, ``/admin/swap`` the new checkpoint in,
  resume. One replica is re-weighting at any moment, so fleet capacity
  never drops by more than one replica. The wait is hygiene, not
  correctness: the engine's weight-generation machinery makes a swap
  under stragglers safe (they finish on the old weights).
- **Fleet goodput.** Every replica-second is attributed to a state
  (``obs.goodput.FLEET_STATE_CAUSES``: serving-ready / serving-unready
  / draining / ejected / scaling-up / scaling-down), so ONE number says
  what fraction of the fleet's tracked replica-seconds was actually
  available to serve tokens — the goodput ledger's discipline extended
  across the fleet, including the autoscaler's transition seconds.
  Every promote/rollback/eject/drain/swap event lands in the deploy
  JSONL (``events_jsonl``) read by ``summarize_run`` / ``report``.
- **Request-level resilience.** End-to-end deadline propagation (a
  client ``timeout_s`` bounds the whole request and rides to the
  replica as ``deadline_s``, so the scheduler's expiry stops decoding
  for departed clients), hedged requests (p95-derived hedge delay,
  first answer wins, the loser cancelled through ``/v1/cancel`` so its
  slot and KV blocks free), a token-bucket retry budget (retries and
  hedges capped as a fraction of recent successes — overload degrades
  into honest errors, never a retry storm), and a per-replica circuit
  breaker (rolling failure/slow-rate window, half-open single-probe
  recovery) that catches the slow-but-200 gray failures the binary
  healthz eject cannot — feeding route-around and the ``breaker_open``
  goodput bucket, never ejection. Drilled end-to-end by
  ``fleet/chaos.py``.
- **Elastic membership + class-aware admission.** ``add_replica`` /
  ``remove_replica`` let the autoscaler (``fleet/autoscaler.py``) grow
  and shrink the fleet through the same drain discipline as a weight
  push, and ``set_admission`` / ``POST /fleet/admission`` sets the
  priority ceiling above which requests are SHED with a terminal 429
  (``"shed": true`` in the body — distinct from a busy 429, which is
  retried on another replica).

Testability follows the scheduler's discipline: the probe and post
functions, clock, and sleep are injectable, so every routing and
ejection decision is provable with scripted replicas and a fake clock —
no sockets, no model (tests/test_fleet.py).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import os
import queue
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from nanodiloco_tpu.obs import flightrec
from nanodiloco_tpu.obs.goodput import FLEET_STATE_CAUSES
from nanodiloco_tpu.obs.tracer import TraceContext
from nanodiloco_tpu.obs.telemetry import (
    OPENMETRICS_CONTENT_TYPE,
    nearest_rank_percentile,
    render_exposition,
)
from nanodiloco_tpu.serve.client import http_get, http_post_json

#: deploy-event kinds the router/controller counters track (one counter
#: family on /metrics; unknown kinds still log, they just don't gauge)
EVENT_KINDS = (
    "promote", "rollback", "rollback_failed", "eject", "drain", "swap",
    "swap_failed", "canary_start", "canary_baseline",
    "canary_baseline_failed", "canary_verdict", "canary_failed",
    "canary_deferred", "slo_burn", "slo_clear",
    # elastic capacity (fleet/autoscaler.py): membership changes, the
    # autoscaler's decisions, spot-preemption recoveries, and admission
    # ceiling moves
    "replica_added", "replica_removed", "scale_up", "scale_down",
    "preempt", "preempt_resume", "shed_level",
    # per-replica circuit breaker (request-level resilience): trip,
    # half-open recovery probe window, and recovery — route-around
    # transitions, never ejections
    "breaker_open", "breaker_half_open", "breaker_close",
)

#: breaker transition -> the deploy-event kind it logs as
_BREAKER_EVENT = {"open": "breaker_open", "half_open": "breaker_half_open",
                  "close": "breaker_close"}
# gauge encoding for nanodiloco_router_breaker_state (unknown reads as
# open: fail toward "this replica is not routable")
_BREAKER_STATE_GAUGE = {"closed": 0, "half_open": 1, "open": 2}


@dataclasses.dataclass(frozen=True)
class Replica:
    """One serve replica the router fronts. ``url`` is the base
    (``http://host:port``); ``blackbox`` is the path of the replica's
    ``serve --blackbox`` dump, attached to its ejection event when the
    file exists."""

    name: str
    url: str
    blackbox: str | None = None


class _Breaker:
    """Per-replica circuit breaker over FORWARD outcomes — the gray-
    failure detector the binary healthz eject cannot be. A rolling
    window of per-attempt results trips ``open`` once the bad rate
    (transport errors and 5xx, plus successes slower than ``slow_s``
    when set) reaches ``failure_rate`` with at least ``min_samples``
    observations. Open cools for ``open_s`` on the injected clock, then
    ``half_open`` admits EXACTLY ONE probe request, whose outcome
    closes the breaker (window cleared) or re-opens it. The breaker
    feeds ROUTE-AROUND (pick ranking) and the ``breaker_open`` goodput
    bucket, never ejection: a gray replica is slow, not dead.

    All mutation happens under the router's lock. ``pending`` holds
    transition names the router drains into deploy events (the drain
    happens on the request path and every health tick, so a transition
    is never silently swallowed by whichever code path advanced it)."""

    def __init__(self, clock: Callable[[], float], *, window: int = 20,
                 min_samples: int = 5, failure_rate: float = 0.5,
                 open_s: float = 10.0,
                 slow_s: float | None = None) -> None:
        self._clock = clock
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.failure_rate = float(failure_rate)
        self.open_s = float(open_s)
        self.slow_s = None if slow_s is None else float(slow_s)
        self.state = "closed"
        self.opens = 0
        self.pending: list[str] = []
        self._results: deque = deque(maxlen=self.window)
        self._opened_at = 0.0
        self._probing = False

    def _trip(self) -> None:
        self.state = "open"
        self._opened_at = self._clock()
        self._probing = False
        self.opens += 1
        self._results.clear()
        self.pending.append("open")

    def note(self, ok: bool, latency_s: float | None = None) -> None:
        """Record one forwarded-attempt outcome."""
        bad = (not ok) or (self.slow_s is not None
                           and latency_s is not None
                           and latency_s > self.slow_s)
        state = self.current()
        if state == "open":
            return  # a straggler attempt launched before the trip:
            # its late result must not extend the cooldown
        if state == "half_open":
            self._probing = False
            if bad:
                self._trip()
            else:
                self.state = "closed"
                self._results.clear()
                self.pending.append("close")
            return
        self._results.append(bad)
        n = len(self._results)
        if (n >= self.min_samples
                and sum(self._results) / n >= self.failure_rate):
            self._trip()

    def current(self) -> str:
        """The state, advancing open -> half_open once ``open_s`` has
        cooled on the injected clock."""
        if (self.state == "open"
                and self._clock() - self._opened_at >= self.open_s):
            self.state = "half_open"
            self._probing = False
            self.pending.append("half_open")
        return self.state

    def rank(self) -> int:
        """Routing preference: 0 closed, 1 half-open awaiting its one
        recovery probe, 2 open (or half-open with the probe already in
        flight). Rank-2 replicas remain PICKABLE when nothing better
        exists — a degraded answer beats a 503."""
        s = self.current()
        if s == "closed":
            return 0
        if s == "half_open" and not self._probing:
            return 1
        return 2


class _ReplicaState:
    """Per-replica tracking: status, readiness, last health stats, and
    per-state wall-clock seconds (the fleet goodput numerator). All
    mutation happens under the router's lock."""

    def __init__(self, replica: Replica, clock: Callable[[], float],
                 status: str = "serving",
                 breaker: _Breaker | None = None) -> None:
        self.replica = replica
        # serving | draining | ejected | scaling_up | scaling_down —
        # the latter two are the autoscaler's transition states: a
        # launched-but-not-yet-ready replica and a retiring one. Their
        # seconds land in their OWN goodput buckets (FLEET_STATE_CAUSES
        # is the closed set), never silently folded into unready.
        self.status = status
        self.ready = False             # last readiness probe
        self.failures = 0              # consecutive unreachable probes
        self.stats: dict = {}          # queue_depth/slots_busy/kv_blocks_free/...
        self.router_inflight = 0       # requests this router has in flight here
        self.breaker = breaker or _Breaker(clock)
        self._clock = clock
        self._since = clock()
        self.seconds = {cause: 0.0 for cause in FLEET_STATE_CAUSES}

    def _bucket(self) -> str:
        if self.status == "serving":
            # a tripped (or half-open) breaker is a named goodput cause:
            # the replica is nominally serving but the router is routing
            # around a gray failure — those seconds must never be booked
            # as ready capacity nor silently dropped
            if self.breaker.current() != "closed":
                return "breaker_open"
            return "serving_ready" if self.ready else "serving_unready"
        return self.status

    def account(self) -> None:
        """Fold elapsed time into the CURRENT state bucket (called on
        every transition and before every snapshot, so the partition is
        exact by construction — the goodput ledger's rule)."""
        now = self._clock()
        self.seconds[self._bucket()] += max(0.0, now - self._since)
        self._since = now

    def set(self, status: str | None = None,
            ready: bool | None = None) -> None:
        self.account()
        if status is not None:
            self.status = status
        if ready is not None:
            self.ready = ready


class FleetRouter:
    """HTTP front + health loop + drain/refill weight pushes over a
    replica set. ``probe``/``post`` are injectable (tests script them);
    the defaults speak the serve wire contract via ``serve/client``."""

    def __init__(
        self,
        replicas: list[Replica],
        *,
        port: int = 0,
        host: str = "0.0.0.0",
        probe: Callable[[Replica], dict] | None = None,
        post: Callable[..., tuple[int, dict]] | None = None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
        health_interval_s: float = 1.0,
        probe_timeout_s: float = 2.0,
        eject_after_failures: int = 3,
        drain_timeout_s: float = 30.0,
        request_timeout_s: float = 600.0,
        hedge_after_s: float | None = None,
        hedge_min_delay_s: float = 0.05,
        hedge_min_samples: int = 16,
        retry_budget_ratio: float = 0.2,
        retry_budget_min: float = 3.0,
        retry_budget_cap: float = 10.0,
        breaker_window: int = 20,
        breaker_min_samples: int = 5,
        breaker_failure_rate: float = 0.5,
        breaker_open_s: float = 10.0,
        breaker_slow_s: float | None = None,
        events_jsonl: str | None = None,
        tracer=None,
        quiet: bool = False,
    ) -> None:
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique; got {names}")
        self._clock = clock
        self._wall = wall
        self._sleep = sleep
        self._probe = probe or self._http_probe
        self._post = post or self._http_post
        self.health_interval_s = float(health_interval_s)
        # per-GET bound for the health probes, deliberately well below
        # the request timeout: the sweep is CONCURRENT (one thread per
        # replica, joined against this bound), so one dead host (SYN
        # timeout, no RST) costs one probe_timeout_s, not (N-1) of them
        # stacked in front of every other replica's ejection
        self.probe_timeout_s = float(probe_timeout_s)
        self.eject_after_failures = int(eject_after_failures)
        self.drain_timeout_s = float(drain_timeout_s)
        self._request_timeout_s = float(request_timeout_s)
        # request-level resilience. Hedge delay: None = adaptive (p95 of
        # recent winner latencies once hedge_min_samples exist, floored
        # at hedge_min_delay_s); > 0 = fixed; <= 0 = hedging disabled.
        self.hedge_min_delay_s = float(hedge_min_delay_s)
        self.hedge_min_samples = int(hedge_min_samples)
        self._hedge_after_s = (None if hedge_after_s is None
                               else float(hedge_after_s))
        # token-bucket retry budget: a retry/hedge costs 1 token, every
        # success deposits retry_budget_ratio (capped) — under fleet-
        # wide failure the budget drains and excess retries become
        # honest errors instead of amplifying into a retry storm
        self.retry_budget_ratio = float(retry_budget_ratio)
        self.retry_budget_cap = float(retry_budget_cap)
        self._retry_tokens = float(retry_budget_min)
        self._breaker_kw = dict(
            window=breaker_window, min_samples=breaker_min_samples,
            failure_rate=breaker_failure_rate, open_s=breaker_open_s,
            slow_s=breaker_slow_s,
        )
        self._resilience = {
            "hedges": 0, "hedge_wins": 0, "retries": 0,
            "retry_budget_exhausted": 0, "deadline_expired": 0,
            "breaker_opens": 0,
        }
        self._latencies: deque = deque(maxlen=512)  # winner latencies
        self.events_jsonl = events_jsonl
        # per-request span sink (obs/tracer.SpanTracer or None): the
        # router records route/forward spans via record_span with ITS
        # OWN clock's timestamps, tagged with the request_id join key —
        # construct the tracer with the same clock callable. Exported
        # through `fleet --trace-out` + `report merge-trace`, these put
        # the router hop on the same Perfetto timeline as the replica's
        # queued/prefill/decode spans for the same request.
        self.tracer = tracer
        self.quiet = quiet
        # SLO burn state (obs/slo action hook, via set_slo_burning or
        # POST /fleet/slo): replica-scope rules make that replica
        # NOT-PREFERRED (routed to only when no clean replica is ready
        # — route-around before any 503-ejection: a burning replica is
        # slow, not dead); fleet-scope rules gate the deploy
        # controller's canary (slo_burning()).
        self._slo_not_preferred: dict[str, set] = {}   # replica -> rule names
        # burning fleet-scope alerts, keyed (rule, target): the monitor
        # fires per (rule, target) pair, and collapsing to rule names
        # would let one target's resolve clear the canary gate while
        # another target's alert still burns
        self._slo_fleet: set = set()                   # {(rule, target)}
        self._req_seq = 0
        self._states = [
            _ReplicaState(r, clock, breaker=self._make_breaker())
            for r in replicas
        ]
        self._by_name = {st.replica.name: st for st in self._states}
        # reentrant: the health tick ejects (and so logs/counts an
        # event) while holding the state lock
        self._lock = threading.RLock()
        # serializes whole push_weights calls (controller thread vs an
        # operator's /fleet/push) — see push_weights
        self._push_lock = threading.Lock()
        self._events_lock = threading.Lock()
        self._counters: dict[str, int] = {}
        # class-aware admission: classes ABOVE this ceiling are shed at
        # the router (terminal 429 with "shed": true) — set by the
        # autoscaler / POST /fleet/admission under fleet burn or
        # forecasted exhaustion; 9 admits everything
        self._admission_max_priority = 9
        self._shed_by_class: dict[int, int] = {}
        # goodput seconds of replicas REMOVED from the fleet (scale-in):
        # retained so the fleet fraction stays every-second-accounted —
        # a retired replica's serving life must not vanish from the
        # denominator
        self._departed_seconds = {cause: 0.0 for cause in FLEET_STATE_CAUSES}
        self._departed_count = 0
        self._t0 = clock()
        self._stop = threading.Event()
        self._health_thread: threading.Thread | None = None
        self._http_thread: threading.Thread | None = None

        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # scrapes must not spam stdout
                pass

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, code: int, doc: dict) -> None:
                self._reply(code, (json.dumps(doc) + "\n").encode(),
                            "application/json")

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._reply(200, router.render_metrics().encode(),
                                OPENMETRICS_CONTENT_TYPE)
                elif path in ("/healthz", "/readyz"):
                    code, doc = router.health()
                    self._reply_json(code, doc)
                elif path == "/fleet/status":
                    self._reply_json(200, router.fleet_stats())
                else:
                    self._reply(404, b"not found\n", "text/plain")

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    doc = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(doc, dict):
                        raise ValueError("body must be a JSON object")
                except ValueError as e:
                    self._reply_json(400, {"error": f"bad JSON: {e}"})
                    return
                if path == "/v1/generate":
                    code, out = router.handle_generate(doc)
                    self._reply_json(code, out)
                elif path == "/fleet/push":
                    code, out = router.handle_push(doc)
                    self._reply_json(code, out)
                elif path == "/fleet/slo":
                    code, out = router.handle_slo(doc)
                    self._reply_json(code, out)
                elif path == "/fleet/admission":
                    code, out = router.handle_admission(doc)
                    self._reply_json(code, out)
                else:
                    self._reply(404, b"not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetRouter":
        self.health_tick()  # replicas routable before the first request
        if self._health_thread is None:
            self._health_thread = threading.Thread(
                target=self._health_loop, name="nanodiloco-fleet-health",
                daemon=True,
            )
            self._health_thread.start()
        if self._http_thread is None:
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="nanodiloco-fleet-http", daemon=True,
            )
            self._http_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        for t in (self._health_thread, self._http_thread):
            if t is not None:
                t.join(timeout=5)
        self._health_thread = self._http_thread = None
        # the final fleet-goodput record: the one number for this
        # router's whole life, next to the deploy events that shaped it
        self._append_jsonl({"fleet_goodput": self.fleet_stats()})

    def _health_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.health_tick()
            except Exception:  # a probe bug must never kill routing
                pass
            self._stop.wait(self.health_interval_s)

    # -- wire defaults (injectable) ------------------------------------------

    def _http_probe(self, replica: Replica) -> dict:
        """One observation of a replica over the wire:
        ``{"reachable", "live", "ready", "stats"}``. The stats ride on
        the health/readiness BODIES (queue_depth, slots_busy,
        kv_blocks_free, deploy_generation, in_flight) — no /metrics
        parse on the health path."""
        out: dict = {"reachable": False, "live": False, "ready": False,
                     "stats": {}}
        try:
            code, body = http_get(replica.url + "/healthz",
                                  timeout=self.probe_timeout_s)
        except (OSError, http.client.HTTPException):
            # HTTPException covers a connection RESET mid-body
            # (IncompleteRead) — a chaos-grade gray failure that is
            # neither a refused socket nor a parsed status
            return out
        out["reachable"] = True
        out["live"] = code == 200
        try:
            doc = json.loads(body)
        except (json.JSONDecodeError, ValueError):
            doc = {}
        for k in ("queue_depth", "slots_busy", "kv_blocks_free",
                  "deploy_generation", "draining", "device_seconds_total",
                  "role"):
            if doc.get(k) is not None:
                out["stats"][k] = doc[k]
        try:
            rcode, rbody = http_get(replica.url + "/readyz",
                                    timeout=self.probe_timeout_s)
            out["ready"] = rcode == 200
            rdoc = json.loads(rbody)
            if isinstance(rdoc, dict) and rdoc.get("in_flight") is not None:
                out["stats"]["in_flight"] = rdoc["in_flight"]
        except (OSError, json.JSONDecodeError, ValueError,
                http.client.HTTPException):
            out["ready"] = False
        return out

    def _http_post(self, replica: Replica, path: str, doc: dict,
                   timeout: float | None = None) -> tuple[int, dict]:
        return http_post_json(
            replica.url + path, doc,
            timeout=self._request_timeout_s if timeout is None else timeout,
        )

    # -- health + ejection ---------------------------------------------------

    def health_tick(self) -> None:
        """One CONCURRENT probe sweep over the non-ejected replicas:
        refresh readiness + load stats, count consecutive failures,
        eject. Probes run in parallel, each under the same per-probe
        bound — sequentially, one blackholed host (SYN timeout, no RST)
        put the LAST replica's detection ``(N-1) * probe_timeout_s``
        behind the dead one; concurrently the whole sweep is bounded by
        roughly one probe's timeout regardless of N."""
        with self._lock:
            states = [st for st in self._states if st.status != "ejected"]
        results: dict[str, dict] = {}

        def _probe_one(st: _ReplicaState) -> None:
            try:
                results[st.replica.name] = self._probe(st.replica) or {}
            except Exception:  # a probe bug must never kill the sweep
                results[st.replica.name] = {}

        threads = []
        for st in states:
            t = threading.Thread(target=_probe_one, args=(st,),
                                 name="nanodiloco-fleet-probe",
                                 daemon=True)
            t.start()
            threads.append(t)
        # real-time join bound (probe threads are real even under an
        # injected clock): 2x covers the probe's two GETs (healthz +
        # readyz), the headroom covers thread scheduling. A probe still
        # hung past the bound reads as this tick's unreachable.
        deadline = time.monotonic() + 2 * self.probe_timeout_s + 1.0
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        for st in states:
            r = results.get(st.replica.name) or {}
            with self._lock:
                if st.status == "ejected":  # a push thread raced us
                    continue
                confirm = self._apply_probe_locked(st, r, confirmed=False)
            if confirm:
                # one flapped /healthz 503 must not eject (the chaos
                # taxonomy's flap_health case): re-probe before calling
                # it the engine loop's death. The replica is unroutable
                # while unconfirmed, and a PERSISTENT 503 still ejects
                # within this same tick.
                try:
                    r2 = self._probe(st.replica) or {}
                except Exception:
                    r2 = {}
                with self._lock:
                    if st.status != "ejected":
                        self._apply_probe_locked(st, r2, confirmed=True)
            # advance the breaker's open->half_open cooldown and flush
            # any transition events it accumulated off the request path
            with self._lock:
                if st.status != "ejected":
                    st.breaker.current()
            self._drain_breaker(st)

    def _apply_probe_locked(self, st: _ReplicaState, r: dict,
                            confirmed: bool) -> bool:
        """Apply one probe observation (caller holds the lock). Returns
        True when the observation was a reachable-but-503 healthz that
        needs a confirming re-probe before the eject."""
        stats = r.get("stats") or {}
        if stats:
            st.stats.update(stats)
        if st.status == "scaling_up":
            # a booting replica is EXPECTED unreachable (process
            # start + compile): no failure budget until it has
            # joined. First live+ready probe promotes it to a
            # routing candidate and closes its scaling_up
            # seconds bucket.
            if r.get("live") and r.get("ready"):
                st.failures = 0
                st.set(status="serving", ready=True)
            return False
        if r.get("live"):
            st.failures = 0
            # a replica draining ITSELF (a push in progress)
            # stays unroutable regardless of its readyz
            st.set(ready=bool(r.get("ready"))
                   and st.status == "serving")
            return False
        if st.status == "scaling_down":
            return False  # retiring: unreachable is the expected end
        if r.get("reachable"):
            # an explicit /healthz 503: the engine loop DIED. It never
            # comes back — eject (after one confirming re-probe, which
            # separates a flapping health endpoint from a dead loop),
            # don't wait out the failure budget meant for restarts.
            if not confirmed:
                st.set(ready=False)
                return True
            self._eject_locked(st, "healthz_503")
            return False
        st.failures += 1
        st.set(ready=False)
        if st.failures >= self.eject_after_failures:
            self._eject_locked(st, "unreachable")
        return False

    def _eject_locked(self, st: _ReplicaState, reason: str) -> None:
        """Eject a replica (caller holds the lock): it stops being a
        routing candidate permanently, and its flight-recorder black
        box — if one landed on disk — is attached to the event, so the
        ejection carries its own forensics."""
        st.set(status="ejected", ready=False)
        fields: dict = {"replica": st.replica.name, "reason": reason}
        bb = self._read_blackbox(st.replica)
        if bb:
            fields["blackbox"] = bb
        self.log_event("eject", **fields)

    def _read_blackbox(self, replica: Replica) -> dict | None:
        path = replica.blackbox
        if not path or not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                doc = json.load(f)
            return {
                "path": path,
                "reason": doc.get("reason"),
                "t_unix": doc.get("t_unix"),
                "events": len(doc.get("events") or []),
            }
        except (OSError, json.JSONDecodeError, ValueError):
            return {"path": path}

    # -- elastic membership (fleet/autoscaler.py) ----------------------------

    def add_replica(self, replica: Replica, *,
                    source: str = "autoscaler") -> None:
        """Join a replica to the fleet in the ``scaling_up`` state: its
        seconds are booked to the ``scaling_up`` goodput bucket until
        the health loop sees it live AND ready, at which point it
        becomes a routing candidate. Boot-time unreachability costs it
        nothing (the failure budget starts once it has joined)."""
        with self._lock:
            if replica.name in self._by_name:
                raise ValueError(
                    f"replica {replica.name!r} is already in the fleet"
                )
            st = _ReplicaState(replica, self._clock, status="scaling_up",
                               breaker=self._make_breaker())
            self._states.append(st)
            self._by_name[replica.name] = st
        self.log_event("replica_added", replica=replica.name,
                       url=replica.url, source=source)

    def remove_replica(self, name: str, *, drain: bool = True,
                       reason: str = "scale_down") -> dict:
        """Retire a replica: flip it to ``scaling_down`` (unroutable),
        optionally drain it and wait — bounded — for in-flight streams,
        then drop it from the fleet. Its per-state seconds are folded
        into the departed ledger so the fleet goodput fraction keeps
        accounting for every second it existed."""
        with self._lock:
            st = self._by_name.get(name)
            if st is None:
                raise ValueError(f"unknown replica {name!r}; replicas "
                                 f"are {self.replica_names()}")
            was_ejected = st.status == "ejected"
            if not was_ejected:
                st.set(status="scaling_down", ready=False)
        if drain and not was_ejected:
            try:
                self._post(st.replica, "/admin/drain", {}, timeout=30.0)
                t0 = self._clock()
                while self._clock() - t0 < self.drain_timeout_s:
                    r = self._probe(st.replica)
                    if not r.get("reachable"):
                        break
                    if (r.get("stats") or {}).get("in_flight", 0) == 0:
                        break
                    self._sleep(0.05)
            except (OSError, ValueError, http.client.HTTPException):
                pass  # an unreachable retiree is already as drained
                # as it will ever be
        with self._lock:
            st.account()
            for k, v in st.seconds.items():
                self._departed_seconds[k] += v
            self._departed_count += 1
            self._states.remove(st)
            del self._by_name[name]
            seconds = {k: round(v, 6) for k, v in st.seconds.items()}
        self.log_event("replica_removed", replica=name, reason=reason,
                       seconds=seconds)
        return {"replica": name, "seconds": seconds}

    # -- class-aware admission (overload shedding) ---------------------------

    def handle_admission(self, doc: dict) -> tuple[int, dict]:
        """POST /fleet/admission: ``{"max_priority": N}`` — set the
        class-shedding ceiling (classes above N get terminal shed
        429s). The autoscaler's wire form; operators can use it too."""
        try:
            mp = self.set_admission(doc.get("max_priority"))
        except ValueError as e:
            return 400, {"error": str(e)}
        return 200, {"max_priority": mp, "shed_by_class": dict(
            sorted(self._shed_by_class.items()))}

    def set_admission(self, max_priority: int, *,
                      reason: str | None = None) -> int:
        """Set the admission ceiling; an actual change logs a
        ``shed_level`` event (the honest record of when the fleet
        started/stopped sacrificing which classes)."""
        if not isinstance(max_priority, int) or isinstance(
                max_priority, bool) or not -1 <= max_priority <= 9:
            raise ValueError(
                f"max_priority must be an integer in [-1, 9]; got "
                f"{max_priority!r}"
            )
        with self._lock:
            changed = self._admission_max_priority != max_priority
            self._admission_max_priority = max_priority
        if changed:
            self.log_event(
                "shed_level", max_priority=max_priority,
                **({"reason": reason} if reason else {}),
            )
        return max_priority

    def admission_max_priority(self) -> int:
        with self._lock:
            return self._admission_max_priority

    # -- request-level resilience (breaker / retry budget / hedging) ---------

    def _make_breaker(self) -> _Breaker:
        return _Breaker(self._clock, **self._breaker_kw)

    def _drain_breaker(self, st: _ReplicaState) -> None:
        """Flush a breaker's pending transitions into deploy events +
        the trip counter (called wherever the breaker may have
        advanced: after a forward outcome, and every health tick)."""
        with self._lock:
            pend, st.breaker.pending = list(st.breaker.pending), []
            for tr in pend:
                if tr == "open":
                    self._resilience["breaker_opens"] += 1
        for tr in pend:
            self.log_event(_BREAKER_EVENT[tr], replica=st.replica.name)

    def _breaker_note(self, st: _ReplicaState, ok: bool,
                      latency_s: float | None = None) -> None:
        with self._lock:
            st.breaker.note(ok, latency_s)
        self._drain_breaker(st)

    def breaker_open_replicas(self) -> list[str]:
        """Serving replicas whose breaker is open or half-open — routed
        around, so NOT usable supply (the autoscaler subtracts them
        from the capacity model's serving set)."""
        with self._lock:
            return sorted(
                st.replica.name for st in self._states
                if st.status == "serving"
                and st.breaker.current() != "closed"
            )

    def _retry_take(self, kind: str) -> bool:
        """Spend one retry-budget token on a retry or hedge. An empty
        bucket refuses (counted): under fleet-wide failure the router
        stops amplifying load and returns the honest error instead."""
        with self._lock:
            if self._retry_tokens >= 1.0:
                self._retry_tokens -= 1.0
                self._resilience[
                    "hedges" if kind == "hedge" else "retries"] += 1
                return True
            self._resilience["retry_budget_exhausted"] += 1
            return False

    def _retry_deposit(self) -> None:
        with self._lock:
            self._retry_tokens = min(
                self.retry_budget_cap,
                self._retry_tokens + self.retry_budget_ratio,
            )

    def _hedge_delay(self) -> float | None:
        """Seconds to wait before hedging, or None when hedging should
        not arm: fixed when ``hedge_after_s`` > 0, disabled when <= 0,
        else the p95 of recent winner latencies (floored at
        ``hedge_min_delay_s``) once enough samples exist — hedge only
        the TAIL, never the typical request."""
        if self._hedge_after_s is not None:
            return self._hedge_after_s if self._hedge_after_s > 0 else None
        with self._lock:
            lats = sorted(self._latencies)
        if len(lats) < self.hedge_min_samples:
            return None
        return max(self.hedge_min_delay_s,
                   nearest_rank_percentile(lats, 0.95))

    def _cancel_request(self, replica: Replica, rid: str) -> None:
        """Fire-and-forget ``/v1/cancel`` to a hedge/deadline loser:
        frees its slot and KV blocks through the scheduler's existing
        ticket-cancel path. Never awaited — a blackholed loser must not
        add its own timeout to the winner's latency."""
        def _run():
            try:
                self._post(replica, "/v1/cancel", {"request_id": rid},
                           timeout=10.0)
            except Exception:
                pass  # best-effort: the replica-side deadline expiry
                # is the backstop for an unreachable loser

        threading.Thread(target=_run, daemon=True,
                         name="nanodiloco-fleet-cancel").start()

    # -- routing -------------------------------------------------------------

    def pick(self, tier: str | None = None) -> _ReplicaState | None:
        """Least-loaded READY replica: lowest queue depth + busy slots
        (+ this router's own in-flight count, which keeps the spread
        honest between health ticks), then MOST free KV blocks, then
        name for determinism. ``tier`` restricts candidates to the
        replicas whose declared role serves that tier (disaggregated
        serving — see ``_tier_match``)."""
        return self._pick_excluding(set(), tier=tier)

    @staticmethod
    def _tier_match(stats: dict, tier: str | None) -> bool:
        """Does a replica's declared role serve ``tier``? A replica
        that never declared one (an older serve build) reads as
        ``both`` — monolithic, eligible for either tier."""
        if tier is None:
            return True
        role = stats.get("role") or "both"
        return role == tier or role == "both"

    def tier_counts(self) -> dict:
        """Serving-and-ready replicas by declared role — the
        ``nanodiloco_fleet_tier_replicas`` gauge and the disagg
        autoscaler's tier census."""
        out = {"prefill": 0, "decode": 0, "both": 0}
        with self._lock:
            for st in self._states:
                if st.status == "serving" and st.ready:
                    role = st.stats.get("role") or "both"
                    out[role if role in out else "both"] += 1
        return out

    def tier_capacity_names(self, tier: str | None) -> list[str]:
        """Replica names that count as USABLE capacity for ``tier``:
        serving, ready, breaker closed, role matching. This is what the
        tier-scoped ``CapacityModel`` targets — an open-breaker or
        draining prefill replica must never count toward decode
        capacity (nor vice versa)."""
        with self._lock:
            return sorted(
                st.replica.name for st in self._states
                if st.status == "serving" and st.ready
                and st.breaker.current() == "closed"
                and self._tier_match(st.stats, tier)
            )

    def _span(self, name: str, t0: float, t1: float, request_id: str,
              ctx=None, **args) -> None:
        if self.tracer is not None:
            self.tracer.record_span(
                name, t0, t1, ctx=ctx, request_id=request_id, **args
            )

    def _accept_trace(self, doc: dict):
        """The route span's causal context: adopt the client's wire
        context (its sampling decision wins) or mint a fresh trace at
        this edge. None when no tracer is installed — every ctx=
        consumer treats None as untraced."""
        if self.tracer is None:
            return None
        wire = TraceContext.from_wire(doc.get("trace_context"))
        if wire is not None:
            return wire.child()
        return self.tracer.new_trace()

    def handle_generate(self, doc: dict) -> tuple[int, dict]:
        """Forward one request with the full resilience stack:

        - **Deadline propagation.** A client ``timeout_s`` becomes the
          router's whole budget (``request_timeout_s`` otherwise): each
          attempt's wire timeout is the REMAINING budget, and the
          forwarded body carries ``deadline_s`` (min of remaining and
          any client-supplied deadline) so the scheduler's expiry stops
          decoding for a departed client instead of burning attributed
          device-seconds. An exhausted budget is an honest 504.
        - **Retry.** One retry on a DIFFERENT replica when an attempt
          answers 503/429-busy/5xx or the socket fails (the health loop
          owns ejection — a forward failure only counts against the
          failure budget). Retries spend the token-bucket retry budget;
          an empty bucket returns the honest error instead of
          amplifying fleet-wide failure into a retry storm.
        - **Hedging.** When the sole attempt outlives the hedge delay
          (p95 of recent winner latencies, or a fixed override), a
          second attempt launches on another ready replica; first
          answer wins, the loser is cancelled through the replica's
          ticket-cancel path (slot + KV blocks freed). Hedges spend the
          same retry budget.
        - A 429 carrying ``"shed": true`` stays TERMINAL fleet policy
          (never retried, never hedged) — the two-429 contract is
          unchanged.

        The ``request_id`` join key is stamped HERE when the client did
        not supply one, and the SAME body — same id — rides every
        attempt: stamping per-attempt would hand the retry/hedge
        replica a different id and break the router-span/replica-span
        trace join for exactly the requests that needed diagnosing
        (merged traces join BOTH attempts of a hedged request). The
        response echoes ``served_by`` (which replica actually answered
        — on a retry or a hedge win that is NOT the first pick)."""
        rid = doc.get("request_id")
        if not isinstance(rid, str) or not rid:
            with self._lock:
                self._req_seq += 1
                rid = f"rtr-{self._req_seq}"
        doc = {**doc, "request_id": rid}
        route_ctx = self._accept_trace(doc)
        timeout_s = doc.pop("timeout_s", None)
        if timeout_s is not None:
            if (isinstance(timeout_s, bool)
                    or not isinstance(timeout_s, (int, float))
                    or not timeout_s > 0):
                return 400, {
                    "error": f"timeout_s must be a positive number of "
                             f"seconds; got {timeout_s!r}",
                    "request_id": rid,
                }
            timeout_s = float(timeout_s)
        t_route = self._clock()
        budget = (timeout_s if timeout_s is not None
                  else self._request_timeout_s)
        deadline_at = t_route + budget
        # class-aware shedding at the front door: a request whose class
        # is above the admission ceiling never touches a replica — the
        # 429 says so explicitly ("shed": true + the class), because it
        # is fleet POLICY, not one replica's backpressure, and the
        # client must not retry it anywhere
        prio = doc.get("priority", 1)
        if not isinstance(prio, int) or isinstance(prio, bool):
            prio = 1  # malformed: let the replica's 400 handle it
        with self._lock:
            ceiling = self._admission_max_priority
            if prio > ceiling:
                self._shed_by_class[prio] = (
                    self._shed_by_class.get(prio, 0) + 1
                )
        if prio > ceiling:
            self._span("route", t_route, self._clock(), rid,
                       ctx=route_ctx, outcome="shed", shed_class=prio)
            return 429, {
                "error": f"priority class {prio} is shed under overload "
                         f"(admitting classes 0..{ceiling})",
                "shed": True,
                "shed_class": prio,
                "max_priority": ceiling,
                "request_id": rid,
            }
        tried: set[str] = set()
        last_429: tuple[int, dict] | None = None
        last_err: tuple[int, dict] | None = None
        outstanding: dict[int, _ReplicaState] = {}
        results: queue.Queue = queue.Queue()
        launched = 0
        hedged = False

        def _launch(st: _ReplicaState, is_hedge: bool) -> None:
            nonlocal launched
            idx = launched
            launched += 1
            name = st.replica.name
            tried.add(name)
            outstanding[idx] = st
            with self._lock:
                st.router_inflight += 1
            remaining = max(0.05, deadline_at - self._clock())
            fwd = dict(doc)
            # every attempt — first pick, retry, hedge — is its OWN
            # child span of the route span, and the replica parents its
            # queued/prefill/decode spans under this attempt's id: a
            # hedge's two legs stay two branches of one tree
            fwd_ctx = route_ctx.child() if route_ctx is not None else None
            if fwd_ctx is not None:
                fwd["trace_context"] = fwd_ctx.to_wire()
            if timeout_s is not None or doc.get("deadline_s") is not None:
                # propagate the deadline replica-side: the scheduler's
                # expiry machinery stops decoding for a client that has
                # already departed (min with any client deadline_s so
                # the router only ever TIGHTENS it)
                d = remaining
                cd = doc.get("deadline_s")
                if (isinstance(cd, (int, float))
                        and not isinstance(cd, bool) and cd > 0):
                    d = min(d, float(cd))
                fwd["deadline_s"] = round(d, 6)
                post_timeout = remaining + 0.25
            else:
                post_timeout = None

            def _run():
                t0 = self._clock()
                try:
                    try:
                        code, out = self._post(
                            st.replica, "/v1/generate", fwd,
                            timeout=post_timeout,
                        )
                    finally:
                        # finally, not per-path: an exception outside
                        # the routed-around classes must never leak the
                        # in-flight count (it feeds the load key — a
                        # leak penalizes this replica forever)
                        with self._lock:
                            st.router_inflight -= 1
                except (OSError, ValueError, http.client.HTTPException):
                    # ValueError = a non-JSON body (misconfigured URL,
                    # an intermediary's error page); HTTPException = a
                    # connection reset mid-body (IncompleteRead): route
                    # around either — a bad replica must cost the
                    # client a retry, not a dropped connection
                    with self._lock:
                        st.failures += 1
                        st.set(ready=False)
                    self._breaker_note(
                        st, ok=False,
                        latency_s=max(0.0, self._clock() - t0))
                    self._span("forward", t0, self._clock(), rid,
                               ctx=fwd_ctx, replica=name, retry=idx > 0,
                               hedge=is_hedge, outcome="error")
                    results.put((is_hedge, idx, st, None, None, t0))
                    return
                # 503 (dead loop or draining) and 429 (backpressure)
                # are routing signals, not breaker badness; 5xx and
                # slow 200s feed the gray-failure window
                self._breaker_note(
                    st, ok=code < 500 or code == 503,
                    latency_s=max(0.0, self._clock() - t0))
                self._span("forward", t0, self._clock(), rid,
                           ctx=fwd_ctx, replica=name, retry=idx > 0,
                           hedge=is_hedge, code=code,
                           outcome=("ok" if code == 200
                                    else "busy" if code == 429
                                    else "unavailable" if code == 503
                                    else "error"))
                results.put((is_hedge, idx, st, code, out, t0))

            threading.Thread(
                target=_run, daemon=True,
                name="nanodiloco-fleet-forward",
            ).start()

        while True:
            now = self._clock()
            if deadline_at - now <= 0:
                # the client's budget is gone: cancel whatever is still
                # in flight (frees replica slots + KV blocks) and say
                # so honestly — never pin a departed client behind the
                # fleet-wide request timeout
                with self._lock:
                    self._resilience["deadline_expired"] += 1
                for lst in outstanding.values():
                    self._cancel_request(lst.replica, rid)
                self._span("route", t_route, now, rid, ctx=route_ctx,
                           outcome="deadline_expired", attempts=launched)
                return 504, {
                    "error": f"deadline exceeded: timeout_s="
                             f"{round(budget, 3)} elapsed before any "
                             f"replica answered",
                    "request_id": rid,
                    **({"tried": sorted(tried)} if tried else {}),
                }
            if not outstanding:
                if launched >= 2:
                    break  # first attempt + one retry/hedge: exhausted
                st = self._pick_excluding(tried)
                if st is None:
                    self._span("route", t_route, self._clock(), rid,
                               ctx=route_ctx, outcome="no_ready_replica")
                    return 503, {"error": "no ready replica",
                                 "request_id": rid,
                                 **({"tried": sorted(tried)}
                                    if tried else {})}
                if launched > 0 and not self._retry_take("retry"):
                    break  # budget empty: the honest error, no storm
                _launch(st, is_hedge=False)
            hedge_delay = None
            if launched == 1 and len(outstanding) == 1 and not hedged:
                hedge_delay = self._hedge_delay()
            wait_s = (deadline_at - self._clock() if hedge_delay is None
                      else min(deadline_at - self._clock(), hedge_delay))
            try:
                # REAL-time wait on the result queue (the attempt
                # threads are real even under an injected clock); the
                # deadline itself is re-checked on the injected clock
                # at the top of every iteration
                is_hedge, idx, st, code, out, t0 = results.get(
                    timeout=max(0.001, wait_s))
            except queue.Empty:
                if hedge_delay is not None:
                    # the sole attempt has outlived the hedge delay:
                    # launch the second attempt on another ready
                    # replica — first answer wins. Armed once per
                    # request, budget-gated like a retry.
                    hedged = True
                    st2 = self._pick_excluding(tried)
                    if st2 is not None and self._retry_take("hedge"):
                        _launch(st2, is_hedge=True)
                continue
            outstanding.pop(idx, None)
            name = st.replica.name
            if code is None:
                continue  # transport failure (marked in the thread)
            if code == 503:
                # the replica's loop is dead or it is draining: route
                # around it now; the health loop decides ejection
                with self._lock:
                    st.set(ready=False)
                continue
            if code == 429:
                if isinstance(out, dict) and out.get("shed"):
                    # a class-SHED 429 is terminal: the replica refused
                    # this class as policy, and every other replica
                    # enforces the same ceiling — retrying would
                    # pointlessly hammer the fleet with traffic it is
                    # deliberately sacrificing. Propagated verbatim
                    # (shed class and ceiling in the body).
                    with self._lock:
                        sc = out.get("shed_class")
                        sc = sc if isinstance(sc, int) else prio
                        self._shed_by_class[sc] = (
                            self._shed_by_class.get(sc, 0) + 1
                        )
                    for lst in outstanding.values():
                        self._cancel_request(lst.replica, rid)
                    self._span("route", t_route, self._clock(), rid,
                               ctx=route_ctx, outcome="shed", replica=name)
                    return 429, {**out, "replica": name,
                                 "request_id": rid}
                # busy 429: queue full HERE, not fleet-wide — try
                # another replica; if every candidate is saturated, the
                # client gets the honest 429 (backpressure), never a
                # fake 503 — with the join key, so the overload is
                # traceable
                # a non-dict body (an intermediary's error page) is
                # wrapped rather than passed through raw: EVERY router
                # response carries the request_id join key, including
                # the ones that needed diagnosing most
                last_429 = (code, {**out, "replica": name,
                                   "request_id": rid}
                            if isinstance(out, dict)
                            else {"error": out, "replica": name,
                                  "request_id": rid})
                continue
            if code >= 500:
                # any other 5xx (chaos-injected or a replica bug):
                # route around it like a transport failure, but keep
                # the body — if every attempt fails the client gets the
                # replica's own error, not a synthesized 503
                last_err = (code, {**out, "replica": name,
                                   "request_id": rid}
                            if isinstance(out, dict)
                            else {"error": out, "replica": name,
                                  "request_id": rid})
                continue
            # first usable answer wins
            if code == 200:
                with self._lock:
                    self._latencies.append(max(0.0, self._clock() - t0))
                    if is_hedge:
                        self._resilience["hedge_wins"] += 1
                self._retry_deposit()
            for lst in outstanding.values():
                # the hedge loser: cancelled through the replica's
                # ticket-cancel path, freeing its slot and KV blocks
                self._cancel_request(lst.replica, rid)
            if isinstance(out, dict):
                out = {**out, "replica": name, "served_by": name}
                out.setdefault("request_id", rid)
                if route_ctx is not None and route_ctx.sampled:
                    out.setdefault("trace_id", route_ctx.trace_id)
            self._span("route", t_route, self._clock(), rid,
                       ctx=route_ctx, outcome="ok", served_by=name,
                       attempts=launched)
            return code, out
        self._span("route", t_route, self._clock(), rid, ctx=route_ctx,
                   outcome="exhausted", attempts=len(tried))
        if last_429 is not None:
            return last_429
        if last_err is not None:
            # a hedged/retried request that lost on BOTH attempts
            # returns ONE honest error (the last replica body), never
            # two answers and never a silent drop
            return last_err
        return 503, {"error": "no replica could take the request",
                     "request_id": rid, "tried": sorted(tried)}

    def _pick_excluding(self, names: set[str],
                        tier: str | None = None) -> _ReplicaState | None:
        with self._lock:
            cands = [st for st in self._states
                     if st.status == "serving" and st.ready
                     and st.replica.name not in names
                     and self._tier_match(st.stats, tier)]
            if not cands:
                return None

            def key(st: _ReplicaState):
                s = st.stats
                load = ((s.get("queue_depth") or 0)
                        + (s.get("slots_busy") or 0) + st.router_inflight)
                free = s.get("kv_blocks_free")
                # breaker route-around OUTRANKS everything: an open-
                # breaker replica is picked only when no closed (or
                # probe-ready half-open) candidate exists — degraded
                # beats 503. SLO not-preferred orders within each
                # breaker rank; load order within each SLO class.
                return (st.breaker.rank(),
                        st.replica.name in self._slo_not_preferred,
                        load, -(free if free is not None else -1),
                        st.replica.name)

            best = min(cands, key=key)
            if best.breaker.rank() == 1:
                # consume the half-open probe slot: exactly one request
                # tests a recovering replica at a time
                best.breaker._probing = True
            return best

    # -- SLO burn state (obs/slo action hook) --------------------------------

    def handle_slo(self, doc: dict) -> tuple[int, dict]:
        """POST /fleet/slo: ``{"rule", "target", "scope", "firing"}`` —
        the wire form of the SLO monitor's action hook (an external
        ``obs-watch`` process observes the fleet and posts burn
        transitions here)."""
        rule = doc.get("rule")
        if not isinstance(rule, str) or not rule:
            return 400, {"error": "rule must be a non-empty string"}
        firing = doc.get("firing")
        if not isinstance(firing, bool):
            return 400, {"error": f"firing must be a boolean; got {firing!r}"}
        scope = doc.get("scope", "replica")
        if scope not in ("replica", "fleet"):
            return 400, {"error": f"scope must be replica|fleet; got {scope!r}"}
        target = doc.get("target")
        if scope == "replica" and target not in self._by_name:
            return 400, {"error": f"unknown replica {target!r}; "
                                  f"replicas are {self.replica_names()}"}
        self.set_slo_burning(rule, target, firing, scope=scope)
        return 200, {"ok": True, **self.slo_state()}

    def set_slo_burning(self, rule: str, target: str | None, firing: bool,
                        *, scope: str = "replica") -> None:
        """Apply one SLO transition. Replica scope: mark/unmark
        ``target`` not-preferred (route-around). Fleet scope: add/
        remove ``rule`` from the set gating the deploy controller's
        canary. Idempotent — only an actual state change logs a
        ``slo_burn``/``slo_clear`` deploy event."""
        with self._lock:
            if scope == "fleet":
                key = (rule, target or "")
                changed = (key in self._slo_fleet) != firing
                if firing:
                    self._slo_fleet.add(key)
                else:
                    self._slo_fleet.discard(key)
                tgt = target or "fleet"
            else:
                rules = self._slo_not_preferred.setdefault(target, set())
                changed = (rule in rules) != firing
                if firing:
                    rules.add(rule)
                else:
                    rules.discard(rule)
                    if not rules:
                        del self._slo_not_preferred[target]
                tgt = target
        if changed:
            self.log_event("slo_burn" if firing else "slo_clear",
                           rule=rule, target=tgt, scope=scope)

    def slo_burning(self) -> bool:
        """True while any FLEET-scope SLO rule burns — the deploy
        controller's canary gate (replica-scope burns route around,
        they do not block deployment: one slow replica must not freeze
        the train->serve loop)."""
        with self._lock:
            return bool(self._slo_fleet)

    def slo_state(self) -> dict:
        with self._lock:
            return self._slo_state_locked()

    def _slo_state_locked(self) -> dict:
        return {
            "slo_fleet_burning": sorted(
                rule if not target else f"{rule}@{target}"
                for rule, target in self._slo_fleet
            ),
            "slo_not_preferred": {
                name: sorted(rules)
                for name, rules in sorted(
                    self._slo_not_preferred.items()
                )
            },
        }

    # -- drain/refill weight pushes ------------------------------------------

    def handle_push(self, doc: dict) -> tuple[int, dict]:
        ckpt = doc.get("checkpoint_dir")
        if not isinstance(ckpt, str) or not ckpt:
            return 400, {"error": "checkpoint_dir must be a non-empty string"}
        step = doc.get("step")
        if step is not None and (isinstance(step, bool)
                                 or not isinstance(step, int)):
            return 400, {"error": f"step must be an integer; got {step!r}"}
        reps = doc.get("replicas")
        if reps is not None and not (
            isinstance(reps, list) and all(isinstance(r, str) for r in reps)
        ):
            return 400, {"error": "replicas must be a list of names"}
        results = self.push_weights(ckpt, step, replicas=reps)
        ok = bool(results) and all(r.get("ok") for r in results)
        return (200 if ok else 502), {"ok": ok, "results": results}

    def push_weights(self, checkpoint_dir: str, step: int | None = None,
                     *, replicas: list[str] | None = None) -> list[dict]:
        """Drain/refill each target replica ONE AT A TIME (fleet
        capacity never drops by more than one replica): drain -> wait
        (bounded) for in-flight streams to finish -> /admin/swap ->
        resume. Returns one result dict per replica, in push order.
        Serialized under a push lock: the deploy controller's thread
        and an operator's /fleet/push must never interleave drains and
        resumes on the same replica (push 2's resume landing mid-push
        1's drain wait would both corrupt the wait and break the
        one-replica-at-a-time capacity invariant)."""
        with self._push_lock:
            targets = [
                st for st in self._states
                if st.status == "serving"
                and (replicas is None or st.replica.name in replicas)
            ]
            if replicas is not None:
                missing = set(replicas) - {st.replica.name
                                           for st in targets}
                if missing:
                    return [{"replica": n, "ok": False,
                             "error": "not a serving replica"}
                            for n in sorted(missing)]
            return [self._push_one(st, checkpoint_dir, step)
                    for st in targets]

    def _push_one(self, st: _ReplicaState, checkpoint_dir: str,
                  step: int | None) -> dict:
        name = st.replica.name
        self.log_event("drain", replica=name,
                       **({"step": step} if step is not None else {}))
        with self._lock:
            st.set(status="draining", ready=False)
        try:
            self._post(st.replica, "/admin/drain", {}, timeout=30.0)
            # bounded wait for in-flight streams: hygiene for a clean
            # canary window, NOT correctness — the engine's generation
            # machinery lets stragglers finish on the old weights even
            # if the swap lands under them
            t0 = self._clock()
            while self._clock() - t0 < self.drain_timeout_s:
                r = self._probe(st.replica)
                if (r.get("stats") or {}).get("in_flight", 0) == 0:
                    break
                self._sleep(0.05)
            body = {"checkpoint_dir": checkpoint_dir}
            if step is not None:
                body["step"] = step
            code, out = self._post(st.replica, "/admin/swap", body)
            ok = code == 200 and isinstance(out, dict) and out.get("swapped")
            self._post(st.replica, "/admin/resume", {}, timeout=30.0)
            with self._lock:
                if ok:
                    st.stats["deploy_generation"] = out.get(
                        "deploy_generation"
                    )
                # routable again immediately; the next health tick
                # re-reads the replica's own readiness. Guarded: the
                # health loop may have EJECTED this replica while the
                # push was mid-flight (it crashed during the drain
                # wait) — resurrecting it would re-route traffic to a
                # corpse and double-count its eventual re-ejection.
                if st.status == "draining":
                    st.set(status="serving", ready=True)
            if ok:
                self.log_event(
                    "swap", replica=name,
                    deploy_generation=out.get("deploy_generation"),
                    **({"step": step} if step is not None else {}),
                )
                return {"replica": name, "ok": True,
                        "deploy_generation": out.get("deploy_generation")}
            err = out.get("error") if isinstance(out, dict) else str(out)
            self.log_event("swap_failed", replica=name, code=code,
                           error=err,
                           **({"step": step} if step is not None else {}))
            return {"replica": name, "ok": False, "code": code,
                    "error": err}
        except (OSError, ValueError, http.client.HTTPException) as e:
            # ValueError covers JSONDecodeError: a replica answering a
            # plain-text body (an old serve without /admin routes, a
            # proxy error page) must be a failed push, not an exception
            # that silently kills the deploy controller's thread;
            # HTTPException covers a connection reset mid-body
            try:
                # the drain may have SUCCEEDED before the failure: a
                # replica left draining admits nothing forever (queued
                # requests expire at their deadlines) — best-effort
                # resume, because a failed push must cost a retry, not
                # a replica's whole capacity
                self._post(st.replica, "/admin/resume", {}, timeout=30.0)
            except (OSError, ValueError, http.client.HTTPException):
                pass
            with self._lock:
                if st.status == "draining":  # not ejected mid-push
                    st.set(status="serving")
                st.failures += 1
            self.log_event("swap_failed", replica=name, error=str(e),
                           **({"step": step} if step is not None else {}))
            return {"replica": name, "ok": False, "error": str(e)}

    # -- events + observability ----------------------------------------------

    def log_event(self, kind: str, **fields) -> dict:
        """One deploy event: counted for /metrics, appended to the
        deploy JSONL (``{"deploy_event": kind, ...}`` — the record shape
        ``summarize_run`` and ``report faults`` read), mirrored into the
        flight-recorder ring, and printed unless quiet."""
        with self._lock:
            self._counters[kind] = self._counters.get(kind, 0) + 1
        rec = {"deploy_event": kind, "t_unix": round(self._wall(), 3),
               **fields}
        self._append_jsonl(rec)
        try:
            flightrec.record_event("deploy", kind=kind, **{
                k: v for k, v in fields.items() if not isinstance(v, dict)
            })
        except Exception:
            pass
        if not self.quiet:
            print(f"[fleet] {json.dumps(rec)}", flush=True)
        return rec

    def _append_jsonl(self, rec: dict) -> None:
        if not self.events_jsonl:
            return
        try:
            d = os.path.dirname(os.path.abspath(self.events_jsonl))
            os.makedirs(d, exist_ok=True)
            with self._events_lock, open(self.events_jsonl, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass  # a full disk must not take down routing

    def replica_names(self) -> list[str]:
        return [st.replica.name for st in self._states]

    def url_of(self, name: str) -> str:
        return self._by_name[name].replica.url

    def state_of(self, name: str) -> dict:
        st = self._by_name[name]
        with self._lock:
            return {"name": name, "status": st.status, "ready": st.ready,
                    "failures": st.failures, "stats": dict(st.stats)}

    def fleet_stats(self) -> dict:
        """The fleet snapshot: readiness counts, per-replica deploy
        generations, event counters, and the fleet goodput fraction —
        replica-seconds spent serving-AND-ready over EVERY tracked
        replica-second (what fraction of the fleet's capacity was
        actually available; drains, ejections, scale transitions, and
        dead time all show up as the gap to 1.0). The denominator is
        the sum of all state buckets, live AND departed: for a static
        fleet that equals wall-clock x replicas exactly, and for an
        autoscaled fleet it keeps every second accounted — a replica
        that existed for 10s contributes 10s, not the router's whole
        elapsed time, and a retired replica's life never vanishes."""
        with self._lock:
            for st in self._states:
                st.account()
            elapsed = max(0.0, self._clock() - self._t0)
            n = len(self._states)
            by_state = dict(self._departed_seconds)
            for st in self._states:
                for k, v in st.seconds.items():
                    by_state[k] += v
            total_s = sum(by_state.values())
            ready_s = by_state["serving_ready"]
            out = {
                "replicas_total": n,
                "replicas_ready": sum(
                    1 for st in self._states
                    if st.status == "serving" and st.ready
                ),
                "replicas_serving": sum(
                    1 for st in self._states if st.status == "serving"
                ),
                "replicas_ejected": sum(
                    1 for st in self._states if st.status == "ejected"
                ),
                "replicas_scaling_up": sum(
                    1 for st in self._states
                    if st.status == "scaling_up"
                ),
                "replicas_departed": self._departed_count,
                # serving-and-ready replicas by declared role — the
                # disaggregated tier census (all "both" for a
                # monolithic fleet)
                "replicas_by_tier": {
                    role: sum(
                        1 for st in self._states
                        if st.status == "serving" and st.ready
                        and (st.stats.get("role") or "both") == role
                    )
                    for role in ("prefill", "decode", "both")
                },
                "deploy_generations": {
                    st.replica.name: st.stats.get("deploy_generation")
                    for st in self._states
                },
                # per-replica attributed device-seconds (from the last
                # health probe's body): where the fleet's dispatch
                # budget is going, replica by replica
                "device_seconds_by_replica": {
                    st.replica.name: st.stats["device_seconds_total"]
                    for st in self._states
                    if st.stats.get("device_seconds_total") is not None
                },
                "events": dict(sorted(self._counters.items())),
                "elapsed_s": round(elapsed, 6),
                "replica_ready_s": round(ready_s, 6),
                "replica_seconds": {
                    st.replica.name: {
                        k: round(v, 6) for k, v in st.seconds.items()
                    }
                    for st in self._states
                },
                # the fleet-total partition by state (departed replicas
                # included): every scale-up/scale-down second is an
                # explicit line item here, never dropped
                "seconds_by_state": {
                    k: round(v, 6) for k, v in by_state.items()
                },
                "fleet_goodput_fraction": (
                    round(ready_s / total_s, 6) if total_s > 0 else None
                ),
                # request-level resilience: hedge/retry/deadline
                # counters, the retry-budget level, and the per-replica
                # breaker picture (summarize_run surfaces these from
                # the final fleet_goodput record)
                **{k: v for k, v in self._resilience.items()},
                "retry_budget_tokens": round(self._retry_tokens, 3),
                "breaker_state": {
                    st.replica.name: st.breaker.state
                    for st in self._states if st.status != "ejected"
                },
                "replicas_breaker_open": sum(
                    1 for st in self._states
                    if st.status == "serving"
                    and st.breaker.state != "closed"
                ),
                "admission_max_priority": self._admission_max_priority,
                "shed_by_class": {
                    c: v for c, v in sorted(self._shed_by_class.items())
                },
                **self._slo_state_locked(),
            }
        return out

    def health(self) -> tuple[int, dict]:
        s = self.fleet_stats()
        doc = {
            "healthy": s["replicas_ready"] > 0,
            "replicas_ready": s["replicas_ready"],
            "replicas_total": s["replicas_total"],
        }
        return (200 if doc["healthy"] else 503), doc

    def render_metrics(self) -> str:
        s = self.fleet_stats()
        families: list = [
            ("nanodiloco_fleet_replicas_ready", "gauge",
             "replicas serving AND ready (routing candidates)",
             [(None, s["replicas_ready"])]),
            ("nanodiloco_fleet_replicas_serving", "gauge",
             "replicas not ejected (draining included)",
             [(None, s["replicas_serving"])]),
            ("nanodiloco_fleet_replicas_total", "gauge",
             "replicas this router was configured with",
             [(None, s["replicas_total"])]),
        ]
        gens = [(name, g) for name, g in
                sorted(s["deploy_generations"].items()) if g is not None]
        if gens:
            families.append((
                "nanodiloco_deploy_generation", "gauge",
                "weight generation each replica serves (bumped by every "
                "hot swap)",
                [({"replica": name}, g) for name, g in gens],
            ))
        families.append((
            "nanodiloco_fleet_events", "counter",
            "deploy events by kind (promote/rollback/eject/drain/swap/"
            "canary)",
            [({"event": k}, v) for k, v in sorted(s["events"].items())]
            + [(None, sum(s["events"].values()))],
        ))
        if s["fleet_goodput_fraction"] is not None:
            families.append((
                "nanodiloco_fleet_goodput_fraction", "gauge",
                "replica-seconds serving-and-ready over all tracked "
                "replica-seconds — the fleet's every-second-accounted "
                "availability number",
                [(None, s["fleet_goodput_fraction"])],
            ))
        dev = s.get("device_seconds_by_replica") or {}
        if dev:
            families.append((
                "nanodiloco_fleet_replica_device_seconds", "counter",
                "attributed dispatch seconds per replica (from the "
                "health probe body) — the fleet's device-second budget "
                "split replica by replica",
                [({"replica": name}, v)
                 for name, v in sorted(dev.items())]
                + [(None, round(sum(dev.values()), 6))],
            ))
        families.append((
            "nanodiloco_fleet_state_seconds", "gauge",
            "replica-seconds by state (departed replicas included) — "
            "scale_up/scale_down transition time is an explicit line "
            "item, never dropped",
            [({"state": k}, v)
             for k, v in sorted(s["seconds_by_state"].items())],
        ))
        families.append((
            "nanodiloco_fleet_admission_max_priority", "gauge",
            "highest priority class the fleet currently admits (9 = "
            "all; lower = class-aware overload shedding active)",
            [(None, s["admission_max_priority"])],
        ))
        if s["shed_by_class"]:
            families.append((
                "nanodiloco_fleet_shed", "counter",
                "requests shed by class-aware admission control, by "
                "priority class (terminal 429s, never retried)",
                [({"priority": str(c)}, v)
                 for c, v in sorted(s["shed_by_class"].items())]
                + [(None, sum(s["shed_by_class"].values()))],
            ))
        families.extend([
            ("nanodiloco_router_hedges", "counter",
             "hedged second attempts launched (first answer wins; the "
             "loser is cancelled replica-side)",
             [(None, s["hedges"])]),
            ("nanodiloco_router_hedge_wins", "counter",
             "hedged requests won by the second attempt",
             [(None, s["hedge_wins"])]),
            ("nanodiloco_router_retries", "counter",
             "retry attempts the token-bucket retry budget admitted",
             [(None, s["retries"])]),
            ("nanodiloco_router_retry_budget_exhausted", "counter",
             "retries/hedges refused because the retry budget was "
             "empty (the anti-retry-storm backstop)",
             [(None, s["retry_budget_exhausted"])]),
            ("nanodiloco_router_deadline_expired", "counter",
             "requests answered 504 because the client deadline "
             "elapsed at the router",
             [(None, s["deadline_expired"])]),
            ("nanodiloco_router_breaker_opens", "counter",
             "circuit-breaker trips (closed/half-open -> open)",
             [(None, s["breaker_opens"])]),
            ("nanodiloco_router_retry_budget_tokens", "gauge",
             "retry-budget tokens currently available",
             [(None, s["retry_budget_tokens"])]),
        ])
        if s["breaker_state"]:
            families.append((
                "nanodiloco_router_breaker_state", "gauge",
                "per-replica circuit-breaker state (0 closed, 1 "
                "half-open, 2 open) — route-around, never ejection",
                [({"replica": name}, _BREAKER_STATE_GAUGE.get(v, 2))
                 for name, v in sorted(s["breaker_state"].items())],
            ))
        families.append((
            "nanodiloco_fleet_slo_burning", "gauge",
            "1 while any fleet-scope SLO rule burns (the canary gate)",
            [(None, int(bool(s["slo_fleet_burning"])))],
        ))
        if s["slo_not_preferred"]:
            families.append((
                "nanodiloco_fleet_replica_not_preferred", "gauge",
                "replicas routed around for a burning replica-scope SLO "
                "(still serving — route-around, not ejection)",
                [({"replica": name}, 1)
                 for name in sorted(s["slo_not_preferred"])],
            ))
        tiers = s.get("replicas_by_tier") or {}
        if tiers:
            families.append((
                "nanodiloco_fleet_tier_replicas", "gauge",
                "serving-and-ready replicas by declared disaggregation "
                "role (prefill/decode/both; a monolithic fleet is all "
                "'both')",
                [({"tier": t}, n) for t, n in sorted(tiers.items())],
            ))
        families.extend(self._extra_metric_families(s))
        return render_exposition(families)

    def _extra_metric_families(self, stats: dict) -> list:
        """Subclass hook (fleet/disagg.py): extra metric families
        appended to the router exposition — the DisaggRouter's handoff
        counters and latency histogram land through here."""
        return []
