"""Predictive autoscaler: the observability plane closing its own loop.

PR 15 built the fleet's senses (collector series, burn rates) and the
router built its actuators (launch/eject, drain/refill); this module
connects trend to action. A control loop watches the collector through
``obs/forecast.py``'s ``CapacityModel`` — queue-depth slope, exhaustion
forecasts, fleet burn state, NEVER raw point gauges — and:

- **scales out** when a resource is forecast to exhaust within
  ``scale_out_horizon_s`` (e.g. ``kv_blocks_free`` trending to 0), by
  launching replicas through a ``ReplicaProvider`` and joining them to
  the ``FleetRouter`` via ``add_replica`` (their boot seconds are
  booked to the ``scaling_up`` goodput bucket — MegaScale's
  every-second-accounted discipline, arXiv:2402.15627, extended to
  elastic capacity);
- **scales in** after sustained headroom (no exhaustion forecast, flat
  or falling queue trend), through the router's drain discipline
  (``remove_replica``) so in-flight streams finish first;
- **rate-limits itself**: a cooldown between scale actions, a max step
  size per action, and hysteresis (``hysteresis_ticks`` consecutive
  agreeing observations before acting) so forecast noise cannot flap
  the fleet;
- **is preemption-aware**: a provider reporting preempted replicas
  (the PR-3 supervisor lifecycle — exit 75 / SIGTERM is "the machine
  was reclaimed", not "the replica failed") gets them relaunched
  IMMEDIATELY, outside the cooldown and step budget, because spot
  capacity only counts as serving capacity if reclaims are recovered
  reflexively;
- **sheds by class** under pressure: when the fleet burns an SLO or
  exhaustion is forecast inside ``shed_horizon_s`` while already at
  ``max_replicas``, the admission ceiling drops one class per tick
  (lowest class first); it recovers one class per tick once the
  pressure clears — so the highest class's SLO holds while load
  exceeds what the fleet can add capacity for.

Everything is injectable (clock, model, provider, router) and the loop
is a plain ``tick()`` method — every decision is provable with scripted
components and a fake clock, no sockets, no model (tier-1 budget).
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import time
from typing import Callable, Protocol

from nanodiloco_tpu.fleet.router import FleetRouter, Replica
from nanodiloco_tpu.obs.forecast import CapacityEstimate, CapacityModel
from nanodiloco_tpu.resilience.supervisor import PREEMPT_EXIT_CODE


class ReplicaProvider(Protocol):
    """Where replicas come from and go to. ``launch`` returns the
    joined ``Replica`` (the autoscaler adds it to the router);
    ``retire`` reclaims one the router already removed; ``preempted``
    lists names whose machines were reclaimed since the last call
    (the autoscaler relaunches them immediately)."""

    def launch(self) -> Replica: ...

    def retire(self, name: str) -> None: ...

    def preempted(self) -> list[str]: ...


class Autoscaler:
    """The control loop. ``run(stop)`` ticks on ``interval_s``;
    ``tick()`` is one observation->decision->action pass returning a
    record of what it saw and did (the drill's assertion surface)."""

    def __init__(
        self,
        router: FleetRouter,
        model: CapacityModel,
        provider: ReplicaProvider,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        interval_s: float = 2.0,
        cooldown_s: float = 20.0,
        max_step: int = 1,
        hysteresis_ticks: int = 2,
        scale_out_horizon_s: float = 60.0,
        scale_in_idle_ticks: int = 5,
        shed_horizon_s: float = 10.0,
        max_shed_floor: int = 0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas; got "
                f"{min_replicas}..{max_replicas}"
            )
        if max_step < 1:
            raise ValueError(f"max_step must be >= 1; got {max_step}")
        if hysteresis_ticks < 1:
            raise ValueError(
                f"hysteresis_ticks must be >= 1; got {hysteresis_ticks}"
            )
        if not 0 <= max_shed_floor <= 9:
            raise ValueError(
                f"max_shed_floor must be in [0, 9]; got {max_shed_floor}"
            )
        self.router = router
        self.model = model
        self.provider = provider
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = float(interval_s)
        self.cooldown_s = float(cooldown_s)
        self.max_step = int(max_step)
        self.hysteresis_ticks = int(hysteresis_ticks)
        self.scale_out_horizon_s = float(scale_out_horizon_s)
        self.scale_in_idle_ticks = int(scale_in_idle_ticks)
        self.shed_horizon_s = float(shed_horizon_s)
        # the lowest the admission ceiling may drop: 0 always protects
        # the most urgent class (shedding class 0 would be the fleet
        # refusing the traffic it exists to protect)
        self.max_shed_floor = int(max_shed_floor)
        self._clock = clock
        self._sleep = sleep
        self._last_scale_t: float | None = None
        self._out_votes = 0   # consecutive ticks voting scale-out
        self._in_votes = 0    # consecutive ticks voting scale-in
        self.ticks = 0

    # -- size + membership ---------------------------------------------------

    def _fleet_size(self) -> int:
        """Replicas that ARE or WILL BE capacity: serving + scaling_up
        (counting a booting replica prevents a second redundant
        scale-out while the first boots — hysteresis alone cannot see
        that)."""
        s = self.router.fleet_stats()
        return s["replicas_serving"] + s["replicas_scaling_up"]

    def _launch(self, n: int, *, why: str, kind: str = "scale_up") -> list[str]:
        names: list[str] = []
        for _ in range(n):
            replica = self.provider.launch()
            self.router.add_replica(replica, source="autoscaler")
            self.router.log_event(kind, replica=replica.name, reason=why)
            names.append(replica.name)
        return names

    def _retire(self, n: int, *, why: str) -> list[str]:
        """Scale in via the router's drain discipline, newest
        autoscaled replicas first (the seed fleet is the stable core),
        never touching a replica below ``min_replicas``."""
        s = self.router.fleet_stats()
        # candidates: ready serving replicas, least-recently added last
        names = [name for name in self.router.replica_names()
                 if self.router.state_of(name)["status"] == "serving"]
        victims = names[::-1][:n]
        out: list[str] = []
        for name in victims:
            if s["replicas_serving"] - len(out) <= self.min_replicas:
                break
            self.router.log_event("scale_down", replica=name, reason=why)
            self.router.remove_replica(name, drain=True,
                                       reason="scale_down")
            self.provider.retire(name)
            out.append(name)
        return out

    # -- the decision --------------------------------------------------------

    def _cooling_down(self, now: float) -> bool:
        return (self._last_scale_t is not None
                and now - self._last_scale_t < self.cooldown_s)

    def _wants_out(self, est: CapacityEstimate) -> str | None:
        """A scale-out reason, or None. Only CONFIDENT forecasts count:
        a two-sample slope from a replica that just booted must not
        grow the fleet."""
        if not est.confident:
            return None
        eta = est.exhaustion_s()
        if eta is not None and eta <= self.scale_out_horizon_s:
            which = ("kv_blocks_free"
                     if eta == est.kv_exhaustion_s else "queue_depth")
            return f"forecast: {which} exhausts in {eta:.1f}s"
        return None

    def _wants_in(self, est: CapacityEstimate) -> bool:
        """Headroom: confident data, nothing forecast to exhaust, and
        the queue trend flat or falling."""
        return (est.confident
                and est.exhaustion_s() is None
                and (est.queue_slope is None or est.queue_slope <= 0.0))

    def tick(self) -> dict:
        """One pass: recover preemptions, observe, decide, act."""
        now = self._clock()
        self.ticks += 1
        rec: dict = {"t": round(now, 3), "tick": self.ticks}

        # 1) preemption recovery — immediate, outside cooldown/step:
        # a reclaimed spot machine is lost capacity RIGHT NOW, and the
        # whole premise of spot serving is reflexive recovery
        for name in self.provider.preempted():
            try:
                self.router.remove_replica(name, drain=False,
                                           reason="preempted")
            except ValueError:
                pass  # already ejected+removed or never joined
            relaunched = self._launch(1, why=f"preempted: {name}",
                                      kind="preempt_resume")
            rec.setdefault("preempt_resumed", []).extend(relaunched)

        # breaker-open replicas are not credible supply: they still
        # scrape (gray failure, not dead), but counting them would let
        # the model see capacity the router is routing around
        breaker = getattr(self.router, "breaker_open_replicas", None)
        excl = getattr(self.model, "set_excluded", None)
        if callable(breaker) and callable(excl):
            excl(breaker())

        est = self.model.estimate(now)
        rec["estimate"] = est.to_dict()
        size = self._fleet_size()
        rec["fleet_size"] = size

        # 2) scaling votes (hysteresis: act only after N consecutive
        # agreeing ticks; any disagreement resets the streak)
        out_reason = self._wants_out(est)
        if out_reason:
            self._out_votes += 1
            self._in_votes = 0
        elif self._wants_in(est):
            self._in_votes += 1
            self._out_votes = 0
        else:
            self._out_votes = self._in_votes = 0

        if (out_reason and self._out_votes >= self.hysteresis_ticks
                and size < self.max_replicas
                and not self._cooling_down(now)):
            n = min(self.max_step, self.max_replicas - size)
            rec["scaled_up"] = self._launch(n, why=out_reason)
            self._last_scale_t = now
            self._out_votes = 0
        elif (self._in_votes >= max(self.hysteresis_ticks,
                                    self.scale_in_idle_ticks)
                and size > self.min_replicas
                and not self._cooling_down(now)):
            n = min(self.max_step, size - self.min_replicas)
            removed = self._retire(n, why="sustained headroom")
            if removed:
                rec["scaled_down"] = removed
                self._last_scale_t = now
            self._in_votes = 0
        elif size < self.min_replicas and not self._cooling_down(now):
            # below the floor (boot, or a preempted replica the
            # provider could not relaunch): refill without a vote
            rec["scaled_up"] = self._launch(
                min(self.max_step, self.min_replicas - size),
                why="below min_replicas",
            )
            self._last_scale_t = now

        # 3) class-aware shedding (an overridable step: the tier-scoped
        # autoscalers in fleet/disagg.py run TWO loops over one fleet,
        # and exactly one of them may own the admission ceiling)
        self._shed_tick(est, rec)
        return rec

    def _shed_tick(self, est: CapacityEstimate, rec: dict) -> None:
        """Class-aware shedding: pressure = fleet-scope SLO burn, or
        exhaustion forecast inside the shed horizon while the fleet
        cannot grow any further. One class per tick each way —
        shedding is an escalation ladder, not a cliff."""
        ceiling = self.router.admission_max_priority()
        pressed = self.router.slo_burning()
        eta = est.exhaustion_s() if est.confident else None
        if (not pressed and eta is not None
                and eta <= self.shed_horizon_s
                and self._fleet_size() >= self.max_replicas):
            pressed = True
        if pressed and ceiling > self.max_shed_floor:
            ceiling = self.router.set_admission(
                ceiling - 1, reason="fleet pressure"
            )
            rec["shed_to"] = ceiling
        elif not pressed and ceiling < 9:
            ceiling = self.router.set_admission(
                ceiling + 1, reason="pressure cleared"
            )
            rec["recovered_to"] = ceiling
        rec["admission_max_priority"] = ceiling

    def run(self, stop=None, max_ticks: int | None = None) -> None:
        """Tick until ``stop`` is set (or ``max_ticks`` exhausted)."""
        n = 0
        while stop is None or not stop.is_set():
            try:
                self.tick()
            except Exception:
                # one bad tick (a racing replica removal, a transient
                # probe error) must not kill the control loop
                pass
            n += 1
            if max_ticks is not None and n >= max_ticks:
                return
            if stop is not None:
                stop.wait(self.interval_s)
            else:
                self._sleep(self.interval_s)


class ProcessReplicaProvider:
    """Replicas as local serve subprocesses — the CLI's provider
    (``fleet --autoscale-template``) and the surge drill's.

    ``template`` is a shell-ish command string with ``{port}`` (and
    optionally ``{name}``) placeholders; each launch picks a free port,
    formats, and spawns the child in its own process group. A child
    that exits with the supervisor's ``PREEMPT_EXIT_CODE`` (75) or dies
    by SIGTERM — the spot reclaim signal — is reported by
    ``preempted()`` exactly once so the autoscaler relaunches it;
    a clean exit is simply gone."""

    def __init__(self, template: str, *, name_prefix: str = "auto",
                 host: str = "127.0.0.1", env: dict | None = None,
                 stdout=None) -> None:
        self.template = template
        self.name_prefix = name_prefix
        self.host = host
        self.env = env
        self._stdout = stdout
        self._seq = 0
        self._procs: dict[str, subprocess.Popen] = {}
        self._ports: dict[str, int] = {}

    @staticmethod
    def _free_port(host: str) -> int:
        import socket

        with socket.socket() as s:
            s.bind((host, 0))
            return s.getsockname()[1]

    def launch(self) -> Replica:
        self._seq += 1
        name = f"{self.name_prefix}{self._seq}"
        port = self._free_port(self.host)
        cmd = self.template.format(port=port, name=name)
        kw: dict = {"start_new_session": True}
        if self.env is not None:
            kw["env"] = {**os.environ, **self.env}
        if self._stdout is not None:
            kw["stdout"] = self._stdout
            kw["stderr"] = subprocess.STDOUT
        proc = subprocess.Popen(shlex.split(cmd), **kw)
        self._procs[name] = proc
        self._ports[name] = port
        return Replica(name=name, url=f"http://{self.host}:{port}")

    def retire(self, name: str) -> None:
        proc = self._procs.pop(name, None)
        self._ports.pop(name, None)
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5)

    def preempted(self) -> list[str]:
        """Names whose child died a preemption death since the last
        call (exit 75 or SIGTERM). Crashed children (any other nonzero
        exit) are dropped from tracking but NOT relaunched here — the
        router's health loop ejects them and the autoscaler's
        min-replicas floor refills; relaunching a crash-looping replica
        at preemption speed would be a fork bomb."""
        gone: list[str] = []
        for name, proc in list(self._procs.items()):
            rc = proc.poll()
            if rc is None:
                continue
            del self._procs[name]
            self._ports.pop(name, None)
            if rc == PREEMPT_EXIT_CODE or rc == -signal.SIGTERM:
                gone.append(name)
        return gone

    def pids(self) -> dict[str, int]:
        """Live child pids by replica name (the drill's preemption
        injection surface)."""
        return {n: p.pid for n, p in self._procs.items()
                if p.poll() is None}

    def stop_all(self) -> None:
        for name in list(self._procs):
            self.retire(name)
