"""Canary-gated promotion: close the train->serve loop.

The trainer emits checkpoints round after round (the DiLoCo premise,
arXiv:2311.08105); the fleet serves whichever one it booted with. This
module is the missing arrow: a controller that WATCHES the training
checkpoint directory, pushes each fresh checkpoint to ONE canary
replica, measures it, and promotes fleet-wide only when the measurement
passes the same ``report compare`` verdict the repo's bench records
already gate on — with automatic rollback (re-swap the prior snapshot)
on regression. Every decision is a deploy-JSONL event next to the
router's drain/swap/eject stream, so ``report faults`` /
``summarize_run`` read one coherent timeline of what the fleet did and
why.

The canary measurement (``canary_bench``) has two legs, and the split
is deliberate (PERF.md honest-measurement entry):

- **Serving legs, over the wire.** Closed-loop clients drive the canary
  replica's real ``/v1/generate`` endpoint — TTFT p50 and
  client-visible decode tokens/s, the keys ``compare_runs`` gates with
  its latency/throughput thresholds. This is the only honest way to
  ask "does the new checkpoint still serve"; it catches a checkpoint
  that loads but stalls, errors, or decodes slowly.
- **Quality leg, from the checkpoint.** ``canary_eval_loss``: mean
  next-token cross-entropy of the candidate snapshot on a DETERMINISTIC
  held-out batch (the synthetic-corpus generator at a held-out seed,
  packed with the run's own tokenizer). The serve API returns token
  ids, not logits, so quality must be computed from the weights — and
  computing it from the same checkpoint the canary swapped in keeps the
  two legs about the same bits. A later checkpoint of a healthy run
  scores lower; a poisoned or torn one scores higher or non-finite —
  non-finite is an AUTOMATIC regression (NaN compares false against
  every threshold, so without the explicit check a NaN checkpoint would
  sail through the gate).

Verdict rules, in order: any canary request error -> fail; non-finite
eval loss -> fail; otherwise ``compare_runs(baseline, candidate)`` (the
``report compare`` engine) with its standard thresholds. The baseline
is the PREVIOUS promoted checkpoint's canary record, measured by the
same harness on the same replica — never a number from a different
machine or a different bench shape. A rolled-back step is remembered
and never re-canaried (a broken checkpoint must not put the fleet in a
canary->rollback loop forever).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable

from nanodiloco_tpu.serve.client import http_post_json


def latest_checkpoint_step(checkpoint_dir: str) -> int | None:
    """Newest COMMITTED checkpoint step in a training
    ``--checkpoint-dir`` (orbax layout; uncommitted/partial saves are
    invisible, which is exactly the property a deploy watcher needs —
    never canary a torn write). None when the directory has no
    checkpoint yet."""
    import os

    if not os.path.isdir(checkpoint_dir):
        return None
    from nanodiloco_tpu.training.checkpoint import CheckpointManager

    mngr = CheckpointManager(checkpoint_dir)
    try:
        return mngr.latest_step
    finally:
        mngr.close()


def canary_eval_loss(checkpoint_dir: str, step: int | None, *,
                     rows: int = 2, seq: int = 64,
                     holdout_seed: int = 20260804) -> float:
    """Mean next-token cross-entropy of a checkpoint's merged snapshot
    on a deterministic held-out batch — the canary's quality leg. The
    batch comes from the synthetic-corpus generator at a seed no
    training run uses (training's corpus seed is 0), packed with the
    tokenizer the checkpoint's sidecar names, so the number is
    comparable checkpoint-to-checkpoint and meaningless to game."""
    import jax.numpy as jnp

    from nanodiloco_tpu.cli import _load_checkpoint_snapshot
    from nanodiloco_tpu.data import get_tokenizer
    from nanodiloco_tpu.data.pipeline import pack_corpus, synthetic_corpus
    from nanodiloco_tpu.models.llama import causal_lm_loss

    cfg, sidecar, params = _load_checkpoint_snapshot(checkpoint_dir, step)
    tok = get_tokenizer(sidecar.get("tokenizer"))
    texts = synthetic_corpus(n_docs=max(8, rows * 2), seed=holdout_seed)
    packed = pack_corpus(texts, tok, seq_length=min(
        seq, cfg.max_position_embeddings
    ))
    batch = jnp.asarray(packed[:rows])
    loss, _aux = causal_lm_loss(params, batch, cfg)
    return float(loss)


def canary_bench(url: str, checkpoint_dir: str, step: int | None, *,
                 clients: int = 2, requests_per_client: int = 2,
                 prompt_len: int = 12, max_new_tokens: int = 16,
                 seed: int = 0, timeout_s: float = 120.0,
                 eval_rows: int = 2, eval_seq: int = 64) -> dict:
    """The closed-loop canary measurement against ONE replica (see
    module docstring for the two-leg split). Returns the summary keys
    ``compare_runs`` gates (``ttft_p50_s``, ``client_tokens_per_sec``,
    ``canary_eval_loss``) plus the raw counts."""
    import random

    from nanodiloco_tpu.obs.telemetry import nearest_rank_percentile

    loss = canary_eval_loss(checkpoint_dir, step,
                            rows=eval_rows, seq=eval_seq)
    rng = random.Random(seed)
    # greedy, prefix-cache-opted-out traffic: the canary must measure
    # the CHECKPOINT, not the cache it is about to invalidate anyway
    docs = [
        {
            "token_ids": [rng.randrange(2, 100) for _ in range(prompt_len)],
            "max_new_tokens": max_new_tokens, "temperature": 0.0,
            "seed": seed + c * 1000 + r, "stop": False,
            "prefix_cache": False,
        }
        for c in range(clients) for r in range(requests_per_client)
    ]
    results: list[dict] = []
    errors: list[dict] = []
    lock = threading.Lock()

    def client(cid: int) -> None:
        for i, doc in enumerate(docs):
            if i % clients != cid:
                continue
            try:
                code, out = http_post_json(
                    url + "/v1/generate", doc, timeout=timeout_s
                )
            except (OSError, ValueError) as e:
                # ValueError = non-JSON body; either way the canary
                # request FAILED and must count as an error (a dead
                # client thread would under-report the request count
                # with errors == 0 — the quiet way to pass the gate)
                code, out = -1, {"error": str(e)}
            with lock:
                (results if code == 200 else errors).append(out)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    ttft = sorted(r["timing"]["ttft_s"] for r in results)
    completion = sum(r["completion_tokens"] for r in results)
    return {
        "canary_step": step,
        "requests": len(results),
        "errors": len(errors),
        "wall_s": round(wall, 3),
        "canary_eval_loss": round(loss, 6) if math.isfinite(loss) else loss,
        "ttft_p50_s": (
            round(nearest_rank_percentile(ttft, 0.50), 4) if ttft else None
        ),
        "client_tokens_per_sec": (
            round(completion / wall, 1) if wall > 0 else None
        ),
    }


class DeployController:
    """Watch a training checkpoint dir; canary, promote, roll back.

    ``router`` is a ``fleet.FleetRouter`` (or anything with its
    ``push_weights``/``log_event``/``replica_names``/``state_of``
    surface — tests script one). ``bench`` is injectable:
    ``bench(url, checkpoint_dir, step) -> summary dict``; the default
    is ``canary_bench``. The canary replica is the FIRST configured
    replica unless named."""

    def __init__(
        self,
        router,
        checkpoint_dir: str,
        *,
        initial_step: int | None = None,
        canary: str | None = None,
        bench: Callable[[str, str, int | None], dict] | None = None,
        poll_interval_s: float = 2.0,
        sleep: Callable[[float], None] = time.sleep,
        max_loss_increase: float = 0.02,
        max_tps_drop: float = 0.2,
        max_latency_increase: float = 0.5,
        bench_kwargs: dict | None = None,
        slo_gate: Callable[[], bool] | None = None,
    ) -> None:
        self.router = router
        self.checkpoint_dir = checkpoint_dir
        self.deployed_step = initial_step
        names = router.replica_names()
        if canary is not None and canary not in names:
            raise ValueError(
                f"canary replica {canary!r} is not in the fleet {names}"
            )
        self.canary = canary or names[0]
        self._bench_kwargs = dict(bench_kwargs or {})
        self._bench = bench or (
            lambda url, ckpt, step: canary_bench(
                url, ckpt, step, **self._bench_kwargs
            )
        )
        self.poll_interval_s = float(poll_interval_s)
        self._sleep = sleep
        self._compare_kwargs = {
            "max_loss_increase": max_loss_increase,
            "max_tps_drop": max_tps_drop,
            "max_latency_increase": max_latency_increase,
        }
        self._baseline: dict | None = None
        # canary SLO gate: a callable answering "is a fleet-scope SLO
        # burning right now?" — default the router's own slo_burning()
        # (wired by obs-watch through POST /fleet/slo). While it
        # answers True, deploy() DEFERS: pushing new weights into a
        # live incident conflates two changes and makes the canary
        # verdict meaningless (the burn would fail a good checkpoint,
        # or mask a bad one). The step is NOT blacklisted — the next
        # poll retries it once the burn clears.
        self._slo_gate = slo_gate if slo_gate is not None else getattr(
            router, "slo_burning", None
        )
        self._deferred_step: int | None = None
        # rolled-back steps: never re-canaried — a broken checkpoint
        # must not trap the fleet in a canary->rollback loop
        self.failed_steps: set[int] = set()

    # -- the watch loop ------------------------------------------------------

    def run(self, stop: threading.Event | None = None,
            max_polls: int | None = None) -> None:
        """Poll until ``stop`` is set (or ``max_polls`` exhausted)."""
        polls = 0
        while stop is None or not stop.is_set():
            self.poll_once()
            polls += 1
            if max_polls is not None and polls >= max_polls:
                return
            if stop is not None:
                stop.wait(self.poll_interval_s)
            else:
                self._sleep(self.poll_interval_s)

    def poll_once(self) -> str | None:
        """One watch step: deploy the newest unseen checkpoint, if any.
        Returns the action taken ("promote"/"rollback"/"canary_failed"/
        "canary_deferred") or None when there was nothing new."""
        try:
            step = latest_checkpoint_step(self.checkpoint_dir)
        except Exception:
            return None  # a mid-write race must not kill the watcher
        if step is None or step == self.deployed_step:
            return None
        if step in self.failed_steps:
            return None
        if self.deployed_step is not None and step < self.deployed_step:
            return None  # never deploy backwards off a stale listing
        return self.deploy(step)

    # -- one deployment ------------------------------------------------------

    def _canary_url(self) -> str:
        return self.router.url_of(self.canary)

    def deploy(self, step: int) -> str:
        """Canary ``step``: establish the baseline (once, by benching
        the CURRENTLY deployed weights on the same canary with the same
        harness), push the candidate to the canary, measure, and
        promote fleet-wide or roll back on the verdict."""
        router = self.router
        if self._slo_gate is not None and self._slo_gate():
            # deferred, not failed: logged ONCE per step (the watch
            # loop re-polls every interval — a long burn must not spam
            # the deploy timeline), retried when the burn clears
            if self._deferred_step != step:
                self._deferred_step = step
                router.log_event("canary_deferred", step=step,
                                 replica=self.canary,
                                 reason="fleet SLO burning")
            return "canary_deferred"
        self._deferred_step = None
        router.log_event("canary_start", step=step, replica=self.canary,
                         baseline_step=self.deployed_step)
        url = self._canary_url()
        if self._baseline is None and self.deployed_step is not None:
            try:
                self._baseline = self._bench(
                    url, self.checkpoint_dir, self.deployed_step
                )
                router.log_event("canary_baseline",
                                 step=self.deployed_step,
                                 record=self._baseline)
            except Exception as e:
                # a missing/unloadable BASELINE is not the candidate's
                # fault (the deployed step's checkpoint may have been
                # GC'd by the trainer's max_to_keep retention):
                # blacklisting the candidate here would stall
                # deployment forever on an error no future checkpoint
                # can clear. Proceed baseline-less — first-deployment
                # semantics: the candidate still fails on request
                # errors or a non-finite eval loss.
                router.log_event("canary_baseline_failed",
                                 step=self.deployed_step,
                                 error=f"{type(e).__name__}: {e}")
        res = router.push_weights(self.checkpoint_dir, step,
                                  replicas=[self.canary])
        if not res or not res[0].get("ok"):
            # NOT blacklisted: a failed PUSH is an infrastructure blip
            # (timeout, replica restarting), not a judgment on the
            # checkpoint — the next poll retries it. The blacklist is
            # reserved for VERDICT failures (a checkpoint that measured
            # bad stays bad).
            router.log_event("canary_failed", step=step,
                             error=(res[0].get("error")
                                    if res else "no canary replica"))
            return "canary_failed"
        try:
            candidate = self._bench(url, self.checkpoint_dir, step)
        except Exception as e:
            candidate = {"errors": 1, "bench_error": str(e)}
        verdict = self.verdict(self._baseline, candidate)
        router.log_event("canary_verdict", step=step, ok=verdict["ok"],
                         regressions=verdict["regressions"],
                         record=candidate)
        if verdict["ok"]:
            others = [
                n for n in router.replica_names()
                if n != self.canary
                and router.state_of(n)["status"] == "serving"
            ]
            failed: list[str] = []
            if others:
                res = router.push_weights(self.checkpoint_dir, step,
                                          replicas=others)
                # a replica whose push failed is LEFT ON THE OLD
                # weights — the promote event must say so, not imply a
                # uniformly updated fleet (the router already logged
                # the per-replica swap_failed detail)
                failed = [r["replica"] for r in res if not r.get("ok")]
            router.log_event(
                "promote", step=step,
                replicas=[self.canary]
                + [n for n in others if n not in failed],
                prior_step=self.deployed_step,
                **({"failed_replicas": failed} if failed else {}),
            )
            self.deployed_step = step
            self._baseline = candidate
            return "promote"
        # ROLLBACK: re-swap the canary to the prior snapshot — the rest
        # of the fleet never saw the regressing weights
        self.failed_steps.add(step)
        restored = self.deployed_step
        rolled = False
        if restored is not None:
            res = router.push_weights(self.checkpoint_dir, restored,
                                      replicas=[self.canary])
            rolled = bool(res) and all(r.get("ok") for r in res)
        if not rolled:
            # the timeline must never CLAIM a rollback that did not
            # happen: the canary is still serving the regressing
            # weights — either the restore push failed (prior
            # checkpoint GC'd, replica died mid-push) or this was a
            # first-ever deployment with NO prior snapshot to restore.
            # Loudest event we have; the operator acts on it.
            router.log_event(
                "rollback_failed", step=step, restored_step=restored,
                regressions=verdict["regressions"],
                **({} if restored is not None
                   else {"error": "no prior deployed step to restore"}),
            )
            return "rollback_failed"
        router.log_event("rollback", step=step, restored_step=restored,
                         regressions=verdict["regressions"])
        return "rollback"

    def verdict(self, baseline: dict | None, candidate: dict) -> dict:
        """The promotion gate (see module docstring for the rule
        order). With no baseline yet (a first-ever deployment), the
        candidate passes unless it errored or its eval loss is
        non-finite — there is nothing to regress against."""
        regressions: list[str] = []
        if candidate.get("errors"):
            regressions.append("canary_request_errors")
        loss = candidate.get("canary_eval_loss")
        if isinstance(loss, float) and not math.isfinite(loss):
            # NaN compares false against every threshold: without this
            # explicit rule a NaN checkpoint would pass the gate
            regressions.append("canary_eval_loss_nonfinite")
        if baseline is not None and not regressions:
            from nanodiloco_tpu.training.metrics import compare_runs

            diff = compare_runs(baseline, candidate,
                                **self._compare_kwargs)
            regressions.extend(diff["regressions"])
        return {"ok": not regressions, "regressions": regressions}
