"""The fleet tier: train -> serve, closed (ROADMAP item 1).

Three coupled pieces over the serving stack (nanodiloco_tpu/serve):

- hot-swap weight deployment lives IN the engine
  (``InferenceEngine.swap_weights`` + the ``/admin/swap`` endpoint):
  params from the latest training checkpoint replace the serving params
  atomically, the paged KV pool survives untouched, in-flight streams
  finish bit-identically on the weights they were admitted under, and
  the prefix cache is invalidated;
- ``router.FleetRouter`` — a small HTTP front over N serve replicas:
  least-loaded routing from queue-depth + ``kv_blocks_free`` gauges,
  ejection on ``/healthz`` 503 with the replica's flight-recorder black
  box attached to the event, drain/refill one-replica-at-a-time weight
  pushes, and a fleet goodput ledger (replica-seconds accounted by
  state);
- ``deploy.DeployController`` — watches the training checkpoint dir,
  canaries each fresh checkpoint on one replica (closed-loop bench +
  held-out eval loss), and promotes fleet-wide only on a passing
  ``report compare`` verdict — automatic rollback on regression;
- ``chaos.ChaosProxy`` + ``chaos.ChaosPlan`` — a deterministic wire-
  level fault injector (the ``resilience/faults.py`` pattern, keyed by
  request ordinal) that sits in front of a real replica so the router's
  resilience stack (deadlines, hedging, retry budget, circuit breakers)
  is drill-verified, not review-anecdote;
- ``disagg.DisaggRouter`` + ``disagg.TierAutoscaler`` /
  ``disagg.DisaggAutoscaler`` — disaggregated prefill/decode serving:
  admissions prefill on one tier, the parked KV ships between replicas
  (``serve/kvship.py``), the stream resumes mid-request on the decode
  tier, and each tier scales independently off its own pinned capacity
  model.

``python -m nanodiloco_tpu fleet --replica URL[,BLACKBOX] ...`` boots
the router (+ the controller with ``--watch-checkpoint-dir``).
"""

from nanodiloco_tpu.fleet.autoscaler import (
    Autoscaler,
    ProcessReplicaProvider,
    ReplicaProvider,
)
from nanodiloco_tpu.fleet.chaos import (
    DRILL_PLAN,
    ChaosPlan,
    ChaosProxy,
    chaos_families,
    proxy_fleet,
)
from nanodiloco_tpu.fleet.deploy import (
    DeployController,
    canary_bench,
    canary_eval_loss,
    latest_checkpoint_step,
)
from nanodiloco_tpu.fleet.disagg import (
    DisaggAutoscaler,
    DisaggRouter,
    TierAutoscaler,
)
from nanodiloco_tpu.fleet.router import EVENT_KINDS, FleetRouter, Replica

__all__ = [
    "Autoscaler",
    "ChaosPlan",
    "ChaosProxy",
    "DRILL_PLAN",
    "DeployController",
    "DisaggAutoscaler",
    "DisaggRouter",
    "EVENT_KINDS",
    "FleetRouter",
    "ProcessReplicaProvider",
    "Replica",
    "ReplicaProvider",
    "TierAutoscaler",
    "canary_bench",
    "canary_eval_loss",
    "chaos_families",
    "latest_checkpoint_step",
    "proxy_fleet",
]
