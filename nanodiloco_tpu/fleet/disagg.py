"""Disaggregated prefill/decode serving: the tiered fleet layer.

DistServe (arXiv:2401.09670) and Splitwise (arXiv:2311.18677) observed
that prefill and decode are different workloads — prefill is one big
compute-bound batch, decode is thousands of tiny latency-bound ticks —
and co-locating them makes every long prompt stretch every live
stream's inter-token latency (the engine's
``decode_interference_ratio`` gauge measures exactly this). The
structural fix: run them on SEPARATE replicas and ship the prefilled
KV cache between them. This module is that fleet layer, built on the
pieces that already exist:

- replicas declare a role (``serve --role prefill|decode|both``) in
  their health bodies; the base router's picker and capacity census
  are tier-aware (``FleetRouter.pick(tier=...)``,
  ``tier_capacity_names``);
- ``DisaggRouter`` reroutes ``/v1/generate``: admission goes to the
  least-loaded PREFILL replica as ``prefill_only`` (the stream
  finishes at its first token and the slot parks), the parked KV rows
  come back through ``/admin/kv/export`` (serve/kvship.py wire
  format), and the payload lands on the least-loaded DECODE replica
  via ``/admin/kv/import``, which resumes the stream mid-request and
  answers with the finished result. Any failure along the handoff —
  prefill unreachable, export 404 (park TTL fired), import 409/429 —
  degrades to ONE honest fallback: a plain monolithic generate on the
  decode tier (re-prefilling there), so a blackholed prefill replica
  costs latency, never a dropped stream;
- ``TierAutoscaler`` / ``DisaggAutoscaler`` scale the tiers
  INDEPENDENTLY: each tier gets its own ``CapacityModel`` pinned every
  tick to that tier's usable replicas (``set_targets`` — an
  open-breaker or draining prefill replica never counts toward decode
  capacity), and the PR-15 fleet burn signals route by name: a TTFT
  burn votes the prefill tier out, a decode-throughput burn votes the
  decode tier out.

Parity bar (pinned by tests/test_disagg.py and the chip_agenda disagg
phase): a disaggregated stream is bit-identical to solo ``generate()``
— the ship format moves the same bits attention would have read
locally, and the PRNG schedule is seed-derived so no sampler state is
lost at the boundary.
"""

from __future__ import annotations

import http.client

from nanodiloco_tpu.fleet.autoscaler import Autoscaler
from nanodiloco_tpu.fleet.router import FleetRouter
from nanodiloco_tpu.obs.forecast import CapacityEstimate
from nanodiloco_tpu.obs.telemetry import Histogram

__all__ = ["DisaggRouter", "TierAutoscaler", "DisaggAutoscaler"]

#: fleet-scope SLO rule-name keywords that vote a tier out: a TTFT burn
#: is prefill starvation (admissions waiting on prompt compute), a
#: decode-throughput burn is decode starvation (ticks behind demand)
PREFILL_BURN_KEYWORDS = ("ttft",)
DECODE_BURN_KEYWORDS = ("decode", "tokens_per_sec")

_WIRE_ERRORS = (OSError, ValueError, http.client.HTTPException)


def _ship_payload_bytes(ship: dict) -> int:
    """Raw (pre-base64) KV bytes in a packed ship doc — the router's
    side of the ship-bytes meter, without decoding the payload."""
    n = 0
    for f in ("k", "v", "ks", "vs"):
        v = ship.get(f)
        if isinstance(v, str):
            n += (len(v) * 3) // 4
    return n


class DisaggRouter(FleetRouter):
    """FleetRouter that splits each request across the tiers.

    Drop-in: with no prefill-tier replica ready (or no decode tier),
    every request takes the base monolithic path unchanged — a fleet
    of ``role=both`` replicas behind a DisaggRouter behaves exactly
    like one behind a FleetRouter. ``handoff_timeout_s`` bounds the
    prefill and export legs (the decode leg runs under the normal
    request timeout: it IS the request)."""

    def __init__(self, replicas, *, handoff_timeout_s: float = 60.0,
                 **kw) -> None:
        super().__init__(replicas, **kw)
        if handoff_timeout_s <= 0:
            raise ValueError(
                f"handoff_timeout_s must be > 0; got {handoff_timeout_s}"
            )
        self.handoff_timeout_s = float(handoff_timeout_s)
        # handoff accounting (under the router lock): completed
        # handoffs, honest fallbacks (and why), and shipped bytes
        self._disagg = {
            "handoffs": 0,
            "fallbacks": 0,
            "ship_bytes": 0,
        }
        self._fallback_reasons: dict[str, int] = {}
        # prefill-done -> payload-on-decode-replica latency (export
        # round-trip + decode-tier pick; the decode stream itself is
        # excluded — it is the request, not the handoff)
        self.hist_handoff = Histogram()

    # -- the two-phase request path ------------------------------------------

    def handle_generate(self, doc: dict) -> tuple[int, dict]:
        # a client explicitly driving the prefill-only protocol (e.g.
        # the chip_agenda harness exporting by hand) bypasses the
        # router's own handoff
        if doc.get("prefill_only"):
            return super().handle_generate(doc)
        rid = doc.get("request_id")
        if not isinstance(rid, str) or not rid:
            with self._lock:
                self._req_seq += 1
                rid = f"rtr-{self._req_seq}"
        # disaggregate only when a replica DECLARED the prefill role and
        # a decode tier is live: a fleet of role=both replicas behaves
        # exactly like one behind a FleetRouter (drop-in), and a prefill
        # pick with no decode tier would park KV nobody will ever import
        pf = (self._pick_excluding(set(), tier="prefill")
              if self.tier_counts().get("prefill") else None)
        if pf is None or not self.tier_capacity_names("decode"):
            return super().handle_generate({**doc, "request_id": rid})

        # the disagg route span's causal context: every handoff leg,
        # the fallback, and (through the injected wire context) the
        # replicas' own spans hang under it — one tree per request
        route_ctx = self._accept_trace(doc)

        # phase 1 — prefill-only admission on the prefill tier. The
        # client's timeout_s stays OFF this leg (it is the base
        # router's deadline machinery; the handoff legs run under
        # handoff_timeout_s and any failure falls back honestly).
        fwd = {k: v for k, v in doc.items() if k != "timeout_s"}
        fwd["request_id"] = rid
        fwd["prefill_only"] = True
        pf_ctx = route_ctx.child() if route_ctx is not None else None
        if pf_ctx is not None:
            fwd["trace_context"] = pf_ctx.to_wire()
        t_req = self._clock()
        t0 = t_req
        with self._lock:
            pf.router_inflight += 1
        try:
            try:
                code, out = self._post(pf.replica, "/v1/generate", fwd,
                                       timeout=self.handoff_timeout_s)
            finally:
                with self._lock:
                    pf.router_inflight -= 1
        except _WIRE_ERRORS:
            # the chaos leg's blackholed-prefill case lands here: mark
            # the replica (health loop owns ejection), re-prefill on
            # the decode tier — one honest retry, zero dropped streams
            with self._lock:
                pf.failures += 1
                pf.set(ready=False)
            self._breaker_note(pf, ok=False,
                               latency_s=max(0.0, self._clock() - t0))
            self._span("handoff_prefill", t0, self._clock(), rid,
                       ctx=pf_ctx, replica=pf.replica.name,
                       outcome="error")
            return self._fallback(doc, rid, "prefill_unreachable",
                                  ctx=route_ctx, t_req=t_req)
        self._breaker_note(pf, ok=code < 500 or code == 503,
                           latency_s=max(0.0, self._clock() - t0))
        self._span("handoff_prefill", t0, self._clock(), rid,
                   ctx=pf_ctx, replica=pf.replica.name, code=code,
                   outcome="ok" if code == 200 else "error")
        if code == 429 and isinstance(out, dict) and out.get("shed"):
            # class-shed stays TERMINAL fleet policy — never rerouted
            self._span("route", t_req, self._clock(), rid,
                       ctx=route_ctx, outcome="shed",
                       replica=pf.replica.name)
            return 429, {**out, "replica": pf.replica.name,
                         "request_id": rid}
        if code != 200 or not isinstance(out, dict):
            return self._fallback(doc, rid, f"prefill_{code}",
                                  ctx=route_ctx, t_req=t_req)
        if out.get("finish_reason") != "prefilled":
            # the stream finished AT its first token (stop token or
            # max_new_tokens == 1): the prefill replica's answer is
            # already complete — nothing to hand off
            out = {**out, "replica": pf.replica.name,
                   "served_by": pf.replica.name}
            out.setdefault("request_id", rid)
            self._span("route", t_req, self._clock(), rid,
                       ctx=route_ctx, outcome="ok",
                       served_by=pf.replica.name)
            return code, out

        # phase 2 — export the parked KV rows + resume cursor
        t_pf_done = self._clock()
        exp_ctx = route_ctx.child() if route_ctx is not None else None
        exp_doc = {"request_id": rid}
        if exp_ctx is not None:
            exp_doc["trace_context"] = exp_ctx.to_wire()
        try:
            ecode, ship = self._post(pf.replica, "/admin/kv/export",
                                     exp_doc,
                                     timeout=self.handoff_timeout_s)
        except _WIRE_ERRORS:
            self._span("handoff_export", t_pf_done, self._clock(), rid,
                       ctx=exp_ctx, replica=pf.replica.name,
                       outcome="error")
            return self._fallback(doc, rid, "export_unreachable",
                                  ctx=route_ctx, t_req=t_req)
        self._span("handoff_export", t_pf_done, self._clock(), rid,
                   ctx=exp_ctx, replica=pf.replica.name, code=ecode,
                   outcome="ok" if ecode == 200 else "error")
        if ecode != 200 or not isinstance(ship, dict):
            # 404 = the park TTL or deadline reclaimed the slot first
            return self._fallback(doc, rid, f"export_{ecode}",
                                  ctx=route_ctx, t_req=t_req)

        # phase 3 — import on the least-loaded decode replica, which
        # resumes the stream and answers with the finished result. A
        # busy 429 tries ONE other decode replica; a 409 (fingerprint
        # mismatch — mixed weight generations mid-push) falls back.
        tried: set[str] = set()
        for _ in range(2):
            dec = self._pick_excluding(tried, tier="decode")
            if dec is None:
                break
            tried.add(dec.replica.name)
            t_imp = self._clock()
            imp_ctx = route_ctx.child() if route_ctx is not None else None
            imp_doc = ship
            if imp_ctx is not None:
                imp_doc = {**ship, "trace_context": imp_ctx.to_wire()}
            with self._lock:
                dec.router_inflight += 1
            try:
                try:
                    icode, iout = self._post(dec.replica,
                                             "/admin/kv/import", imp_doc)
                finally:
                    with self._lock:
                        dec.router_inflight -= 1
            except _WIRE_ERRORS:
                self._breaker_note(dec, ok=False)
                self._span("handoff_import", t_imp, self._clock(), rid,
                           ctx=imp_ctx, replica=dec.replica.name,
                           outcome="error")
                continue
            self._breaker_note(dec, ok=icode < 500)
            self._span("handoff_import", t_imp, self._clock(), rid,
                       ctx=imp_ctx, replica=dec.replica.name, code=icode,
                       outcome=("ok" if icode == 200
                                else "busy" if icode == 429 else "error"))
            if icode == 200 and isinstance(iout, dict):
                with self._lock:
                    self._disagg["handoffs"] += 1
                    self._disagg["ship_bytes"] += _ship_payload_bytes(ship)
                self.hist_handoff.observe(max(0.0, t_imp - t_pf_done))
                self._span("handoff", t_pf_done, t_imp, rid,
                           ctx=(route_ctx.child()
                                if route_ctx is not None else None),
                           prefilled_by=pf.replica.name,
                           decoded_by=dec.replica.name)
                t_done = self._clock()
                self._span("route", t_req, t_done, rid, ctx=route_ctx,
                           outcome="ok", served_by=dec.replica.name,
                           prefilled_by=pf.replica.name)
                # per-phase TTFT waterfall, from the clocks that own
                # each boundary: the prefill replica's own queue/compute
                # split, the router's ship window (export leg + decode
                # pick), and the import leg's admission overhead (wire +
                # KV mapping, the decode work itself subtracted out)
                pf_timing = out.get("timing") or {}
                imp_leg_s = max(0.0, self._clock() - t_imp)
                it = iout.get("timing") or {}
                phases = {}
                if isinstance(pf_timing.get("queued_s"), (int, float)):
                    phases["queue_s"] = round(
                        float(pf_timing["queued_s"]), 6)
                    if isinstance(pf_timing.get("ttft_s"), (int, float)):
                        phases["prefill_s"] = round(max(
                            0.0, float(pf_timing["ttft_s"])
                            - float(pf_timing["queued_s"])), 6)
                phases["ship_s"] = round(max(0.0, t_imp - t_pf_done), 6)
                if isinstance(it.get("total_s"), (int, float)):
                    phases["decode_admission_s"] = round(max(
                        0.0, imp_leg_s - float(it["total_s"])), 6)
                iout = {**iout, "replica": dec.replica.name,
                        "served_by": dec.replica.name,
                        "prefilled_by": pf.replica.name,
                        "disagg": "handoff",
                        # END-TO-END first-token latency: router receipt
                        # to the prefill reply (the first token exists
                        # from then on) — the decode replica's own
                        # timing.ttft_s only covers the resumed stream
                        "handoff_ttft_s": round(t_pf_done - t_req, 6),
                        "handoff_phases": phases}
                iout.setdefault("request_id", rid)
                if route_ctx is not None and route_ctx.sampled:
                    iout.setdefault("trace_id", route_ctx.trace_id)
                return 200, iout
            if icode == 429:
                continue  # this decode replica is full; try another
            break  # 409 mismatch / 400 / 5xx: fall back, don't spray
        return self._fallback(doc, rid, "import_failed",
                              ctx=route_ctx, t_req=t_req)

    def _fallback(self, doc: dict, rid: str, reason: str,
                  ctx=None, t_req: float | None = None) -> tuple[int, dict]:
        """The ONE honest retry: a plain monolithic generate on the
        decode tier (which re-prefills locally). Counted per reason;
        when even that finds no decode replica, the base router's full
        resilience stack is the last resort. ``ctx``/``t_req`` carry
        the disagg route span: each fallback attempt is its own child
        span tagged with the reason, and the route span closes with
        ``outcome="fallback"`` on every path out of here."""
        with self._lock:
            self._disagg["fallbacks"] += 1
            self._fallback_reasons[reason] = (
                self._fallback_reasons.get(reason, 0) + 1
            )
        fwd = {k: v for k, v in doc.items() if k != "prefill_only"}
        fwd["request_id"] = rid
        tried: set[str] = set()
        for _ in range(2):
            st = self._pick_excluding(tried, tier="decode")
            if st is None:
                break
            tried.add(st.replica.name)
            fb_ctx = ctx.child() if ctx is not None else None
            if fb_ctx is not None:
                fwd = {**fwd, "trace_context": fb_ctx.to_wire()}
            t0 = self._clock()
            with self._lock:
                st.router_inflight += 1
            try:
                try:
                    code, out = self._post(st.replica, "/v1/generate", fwd)
                finally:
                    with self._lock:
                        st.router_inflight -= 1
            except _WIRE_ERRORS:
                self._breaker_note(st, ok=False)
                self._span("fallback", t0, self._clock(), rid,
                           ctx=fb_ctx, replica=st.replica.name,
                           reason=reason, outcome="error")
                continue
            self._breaker_note(st, ok=code < 500 or code == 503)
            self._span("fallback", t0, self._clock(), rid, ctx=fb_ctx,
                       replica=st.replica.name, reason=reason, code=code,
                       outcome=("ok" if code == 200
                                else "busy" if code == 429
                                else "unavailable" if code == 503
                                else "error"))
            if code in (429, 503) and not (
                    isinstance(out, dict) and out.get("shed")):
                continue
            if isinstance(out, dict):
                out = {**out, "replica": st.replica.name,
                       "served_by": st.replica.name,
                       "disagg": "fallback"}
                out.setdefault("request_id", rid)
                if ctx is not None and ctx.sampled:
                    out.setdefault("trace_id", ctx.trace_id)
            else:
                # a non-dict body must still carry the join key — the
                # fallback path is exactly where a client needs it
                out = {"error": out, "replica": st.replica.name,
                       "disagg": "fallback", "request_id": rid}
            if ctx is not None and t_req is not None:
                self._span("route", t_req, self._clock(), rid, ctx=ctx,
                           outcome="fallback", reason=reason,
                           served_by=st.replica.name)
            return code, out
        # last resort: the base router's full resilience stack, its
        # route span nested under the disagg route span via the wire
        # context so the trace stays one tree
        if ctx is not None:
            fwd = {**fwd, "trace_context": ctx.to_wire()}
        code, out = super().handle_generate(fwd)
        if ctx is not None and t_req is not None:
            self._span("route", t_req, self._clock(), rid, ctx=ctx,
                       outcome="fallback", reason=reason)
        return code, out

    # -- observability --------------------------------------------------------

    def fleet_stats(self) -> dict:
        out = super().fleet_stats()
        with self._lock:
            d = dict(self._disagg)
            d["fallbacks_by_reason"] = dict(
                sorted(self._fallback_reasons.items())
            )
        snap = self.hist_handoff.snapshot()
        if snap["count"]:
            d["handoff_count"] = snap["count"]
            d["handoff_seconds_sum"] = round(snap["sum"], 6)
        out["disagg"] = d
        return out

    def _extra_metric_families(self, stats: dict) -> list:
        d = stats.get("disagg") or {}
        fams: list = [
            ("nanodiloco_fleet_handoffs", "counter",
             "completed prefill->decode KV handoffs (the stream's "
             "prefill and decode ran on different replicas)",
             [(None, d.get("handoffs", 0))]),
            ("nanodiloco_fleet_handoff_fallbacks", "counter",
             "handoffs degraded to a monolithic decode-tier generate "
             "(prefill unreachable, export expired, import refused) — "
             "one honest retry, never a dropped stream",
             [(None, d.get("fallbacks", 0))]),
            ("nanodiloco_fleet_ship_bytes", "counter",
             "raw KV payload bytes the router moved between tiers "
             "(pre-base64)",
             [(None, d.get("ship_bytes", 0))]),
        ]
        snap = self.hist_handoff.snapshot()
        if snap["count"]:
            fams.append((
                "nanodiloco_fleet_handoff_seconds", "histogram",
                "prefill completion to payload landing on the decode "
                "replica (export round-trip + tier pick; the decode "
                "stream itself is the request, not the handoff)",
                snap,
            ))
        return fams


class TierAutoscaler(Autoscaler):
    """Autoscaler scoped to ONE tier of a disaggregated fleet.

    Differences from the base loop, all tier-scoping:

    - the capacity model is pinned every tick to this tier's USABLE
      replicas (``FleetRouter.tier_capacity_names`` — serving, ready,
      breaker closed, role matching), so a draining or open-breaker
      prefill replica never counts toward decode capacity;
    - fleet size / retirement candidates count only this tier's
      replicas (plus the boots THIS loop launched, tracked by name —
      a booting replica has not declared a role yet);
    - a fleet-scope SLO burn whose rule name matches this tier's
      keywords (TTFT -> prefill, decode throughput -> decode) is a
      scale-out vote even before a forecast confirms it;
    - at most one tier's loop may own the admission ceiling
      (``manage_admission``) — two shed ladders over one fleet would
      fight each other one class per tick.

    The provider must launch replicas OF THIS TIER (e.g. a
    ``ProcessReplicaProvider`` whose template carries ``--role``)."""

    def __init__(self, router: FleetRouter, model, provider, *,
                 tier: str, manage_admission: bool = False,
                 burn_keywords: tuple = None, **kw) -> None:
        if tier not in ("prefill", "decode"):
            raise ValueError(
                f"tier must be 'prefill' or 'decode'; got {tier!r}"
            )
        super().__init__(router, model, provider, **kw)
        self.tier = tier
        self.manage_admission = bool(manage_admission)
        if burn_keywords is None:
            burn_keywords = (PREFILL_BURN_KEYWORDS if tier == "prefill"
                             else DECODE_BURN_KEYWORDS)
        self.burn_keywords = tuple(burn_keywords)
        self._mine: set[str] = set()

    def _in_tier(self, name: str) -> bool:
        st = self.router.state_of(name)
        if st["status"] == "serving":
            role = st["stats"].get("role") or "both"
            return role == self.tier or role == "both"
        if st["status"] == "scaling_up":
            return name in self._mine
        return False

    def _fleet_size(self) -> int:
        return sum(1 for n in self.router.replica_names()
                   if self._in_tier(n))

    def _launch(self, n: int, *, why: str,
                kind: str = "scale_up") -> list[str]:
        names = super()._launch(n, why=f"[{self.tier}] {why}", kind=kind)
        self._mine.update(names)
        return names

    def _retire(self, n: int, *, why: str) -> list[str]:
        names = [nm for nm in self.router.replica_names()
                 if self._in_tier(nm)
                 and self.router.state_of(nm)["status"] == "serving"]
        victims = names[::-1][:n]
        out: list[str] = []
        for name in victims:
            if len(names) - len(out) <= self.min_replicas:
                break
            self.router.log_event("scale_down", replica=name,
                                  reason=f"[{self.tier}] {why}")
            self.router.remove_replica(name, drain=True,
                                       reason="scale_down")
            self.provider.retire(name)
            self._mine.discard(name)
            out.append(name)
        return out

    def _burning_for_tier(self) -> str | None:
        """A fleet-scope burning rule whose name routes to this tier,
        or None. Rule names carry the signal: the SLO config's TTFT
        rule names contain 'ttft', the throughput rules 'decode' /
        'tokens_per_sec' — the PR-15 burn signals driving the split."""
        slo = getattr(self.router, "slo_state", None)
        if not callable(slo):
            return None
        for rule in slo().get("slo_fleet_burning") or []:
            low = rule.lower()
            if any(k in low for k in self.burn_keywords):
                return rule
        return None

    def _wants_out(self, est: CapacityEstimate) -> str | None:
        reason = super()._wants_out(est)
        if reason:
            return reason
        rule = self._burning_for_tier()
        if rule is not None:
            return f"slo burn: {rule} -> {self.tier} tier"
        return None

    def _shed_tick(self, est: CapacityEstimate, rec: dict) -> None:
        if self.manage_admission:
            super()._shed_tick(est, rec)
        else:
            rec["admission_max_priority"] = (
                self.router.admission_max_priority()
            )

    def tick(self) -> dict:
        # pin the model to THIS tier's usable supply before estimating
        # (the small-fix satellite: capacity is tier-scoped, not
        # fleet-global)
        tgt = getattr(self.model, "set_targets", None)
        names = getattr(self.router, "tier_capacity_names", None)
        if callable(tgt) and callable(names):
            tgt(names(self.tier))
        rec = super().tick()
        rec["tier"] = self.tier
        return rec


class DisaggAutoscaler:
    """Two tier-scoped control loops over one fleet, ticked together.

    The prefill tier is sized by arrival pressure (queue depth and its
    slope are prompt-compute demand on prefill replicas), the decode
    tier by live slots and the ``kv_blocks_free`` forecast — each
    through its OWN tier-pinned ``CapacityModel``, scaling
    independently as the traffic mix shifts. The decode loop owns the
    admission ceiling (overload saturates decode capacity first; one
    shed ladder, not two fighting)."""

    def __init__(self, prefill: TierAutoscaler,
                 decode: TierAutoscaler) -> None:
        if prefill.tier != "prefill" or decode.tier != "decode":
            raise ValueError(
                "DisaggAutoscaler needs (prefill-tier, decode-tier) "
                f"loops; got {prefill.tier!r}, {decode.tier!r}"
            )
        if prefill.manage_admission and decode.manage_admission:
            raise ValueError(
                "only one tier's loop may manage the admission ceiling"
            )
        self.prefill = prefill
        self.decode = decode
        self.interval_s = min(prefill.interval_s, decode.interval_s)

    def tick(self) -> dict:
        return {"prefill": self.prefill.tick(),
                "decode": self.decode.tick()}

    def run(self, stop=None, max_ticks: int | None = None) -> None:
        n = 0
        while stop is None or not stop.is_set():
            try:
                self.tick()
            except Exception:
                pass  # one bad tick must not kill the control loop
            n += 1
            if max_ticks is not None and n >= max_ticks:
                return
            if stop is not None:
                stop.wait(self.interval_s)
            else:
                self.prefill._sleep(self.interval_s)
