// Standalone sanitizer harness for the tokenshard native layer — the
// "race detection / sanitizers" aux subsystem (SURVEY §5: absent in the
// reference, which has no native code; this framework's threaded C++
// data path earns one). Exercises every extern "C" entry point,
// including the multithreaded gather and the error paths, under
// whatever -fsanitize= flags the build passes:
//
//   g++ -std=c++17 -g -fsanitize=address,undefined csrc/tokenshard.cpp \
//       csrc/sanitize_test.cpp -o /tmp/ts_asan -lpthread && /tmp/ts_asan
//   g++ -std=c++17 -g -fsanitize=thread csrc/tokenshard.cpp \
//       csrc/sanitize_test.cpp -o /tmp/ts_tsan -lpthread && /tmp/ts_tsan
//
// tests/test_tokenshard.py builds and runs both when g++ is available.

// assert() carries the test's side effects — an NDEBUG build must not
// silently delete them and still print OK
#undef NDEBUG
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

struct Shard;
extern "C" {
int ts_write(const char*, const int32_t*, uint64_t, uint64_t);
Shard* ts_open(const char*);
uint64_t ts_n_seqs(const Shard*);
uint64_t ts_seq_len(const Shard*);
void ts_close(Shard*);
int ts_gather(const Shard*, const uint64_t*, uint64_t, int32_t*, int);
void ts_shuffled_indices(uint64_t, uint64_t, uint64_t, uint64_t, uint64_t*);
}

int main(int argc, char** argv) {
  const std::string dir = argc > 1 ? argv[1] : "/tmp";
  const std::string path = dir + "/sanitize_test.tshrd";
  constexpr uint64_t kSeqs = 1000, kLen = 96;

  // write a shard whose every cell is derivable from its position
  std::vector<int32_t> data(kSeqs * kLen);
  for (uint64_t i = 0; i < data.size(); ++i)
    data[i] = static_cast<int32_t>(i % 100003);
  assert(ts_write(path.c_str(), data.data(), kSeqs, kLen) == 0);

  // error paths: missing file, bad magic
  assert(ts_open((dir + "/definitely_missing.tshrd").c_str()) == nullptr);
  {
    const std::string bad = dir + "/bad_magic.tshrd";
    FILE* f = fopen(bad.c_str(), "wb");
    const char junk[32] = "NOTASHARDFILE";
    fwrite(junk, 1, sizeof junk, f);
    fclose(f);
    assert(ts_open(bad.c_str()) == nullptr);
  }

  Shard* s = ts_open(path.c_str());
  assert(s && ts_n_seqs(s) == kSeqs && ts_seq_len(s) == kLen);

  // shuffled indices: a permutation, deterministic in (seed,epoch,worker)
  std::vector<uint64_t> perm(kSeqs), perm2(kSeqs), seen(kSeqs, 0);
  ts_shuffled_indices(kSeqs, 7, 3, 1, perm.data());
  ts_shuffled_indices(kSeqs, 7, 3, 1, perm2.data());
  assert(memcmp(perm.data(), perm2.data(), kSeqs * 8) == 0);
  for (uint64_t v : perm) { assert(v < kSeqs); seen[v]++; }
  for (uint64_t c : seen) assert(c == 1);
  ts_shuffled_indices(kSeqs, 7, 4, 1, perm2.data());
  assert(memcmp(perm.data(), perm2.data(), kSeqs * 8) != 0);

  // gathers: single-thread, many threads, more threads than rows, empty
  std::vector<int32_t> out(kSeqs * kLen);
  for (int threads : {1, 8, 64, 0}) {
    memset(out.data(), -1, out.size() * 4);
    assert(ts_gather(s, perm.data(), kSeqs, out.data(), threads) == 0);
    for (uint64_t r = 0; r < kSeqs; ++r)
      assert(memcmp(out.data() + r * kLen, data.data() + perm[r] * kLen,
                    kLen * 4) == 0);
  }
  uint64_t few[3] = {0, kSeqs - 1, kSeqs / 2};
  assert(ts_gather(s, few, 3, out.data(), 16) == 0);  // threads > rows
  assert(ts_gather(s, few, 0, out.data(), 4) == 0);   // empty gather
  uint64_t oob = kSeqs;                               // out-of-range row
  assert(ts_gather(s, &oob, 1, out.data(), 2) == -1);

  ts_close(s);
  ts_close(nullptr);  // must be a no-op
  std::printf("sanitize_test OK\n");
  return 0;
}
