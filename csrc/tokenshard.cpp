// tokenshard: memory-mapped token storage + deterministic shuffled batch
// assembly for the data pipeline.
//
// The reference's data path is torch DataLoader + HF datasets map-tokenize
// (ref nanodiloco/training_utils/utils.py:45-55, main.py:79-96) — Python
// objects per example, per-batch padding, GIL-bound collation. This native
// layer replaces the hot path with:
//   - an mmap'd shard file of fixed-length int32 sequences (zero-copy
//     reads, page-cache friendly for epoch re-reads),
//   - multithreaded row gather into a caller-provided batch buffer,
//   - a deterministic in-library shuffle (splitmix64-seeded Fisher-Yates)
//     so every host computes identical batch order with no coordination.
//
// File layout (little-endian):
//   [0:8)   magic "TSHRD\x01\x00\x00"
//   [8:16)  uint64 n_seqs
//   [16:24) uint64 seq_len
//   [24:..) int32 data, row-major [n_seqs, seq_len]
//
// C ABI only (consumed via ctypes from nanodiloco_tpu/data/tokenshard.py).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

constexpr char kMagic[8] = {'T', 'S', 'H', 'R', 'D', 1, 0, 0};
constexpr uint64_t kHeaderBytes = 24;

struct Shard {
  int fd = -1;
  const uint8_t* map = nullptr;
  uint64_t map_bytes = 0;
  uint64_t n_seqs = 0;
  uint64_t seq_len = 0;
  const int32_t* data = nullptr;
};

// splitmix64: tiny, well-mixed PRNG — stable across platforms/compilers,
// unlike std::mt19937 usage patterns.
inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

// ---- writer ---------------------------------------------------------------

int ts_write(const char* path, const int32_t* data, uint64_t n_seqs,
             uint64_t seq_len) {
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  uint8_t header[kHeaderBytes];
  memcpy(header, kMagic, 8);
  memcpy(header + 8, &n_seqs, 8);
  memcpy(header + 16, &seq_len, 8);
  if (fwrite(header, 1, kHeaderBytes, f) != kHeaderBytes) {
    fclose(f);
    return -2;
  }
  const uint64_t total = n_seqs * seq_len;
  if (fwrite(data, sizeof(int32_t), total, f) != total) {
    fclose(f);
    return -3;
  }
  return fclose(f) == 0 ? 0 : -4;
}

// ---- reader ---------------------------------------------------------------

Shard* ts_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<uint64_t>(st.st_size) < kHeaderBytes) {
    close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  const uint8_t* bytes = static_cast<const uint8_t*>(map);
  if (memcmp(bytes, kMagic, 8) != 0) {
    munmap(map, st.st_size);
    close(fd);
    return nullptr;
  }
  auto* s = new Shard;
  s->fd = fd;
  s->map = bytes;
  s->map_bytes = st.st_size;
  memcpy(&s->n_seqs, bytes + 8, 8);
  memcpy(&s->seq_len, bytes + 16, 8);
  if (s->map_bytes < kHeaderBytes + s->n_seqs * s->seq_len * sizeof(int32_t)) {
    munmap(map, st.st_size);
    close(fd);
    delete s;
    return nullptr;
  }
  s->data = reinterpret_cast<const int32_t*>(bytes + kHeaderBytes);
  // epoch reads sweep the whole file; tell the kernel
  madvise(map, st.st_size, MADV_WILLNEED);
  return s;
}

uint64_t ts_n_seqs(const Shard* s) { return s->n_seqs; }
uint64_t ts_seq_len(const Shard* s) { return s->seq_len; }

void ts_close(Shard* s) {
  if (!s) return;
  munmap(const_cast<uint8_t*>(s->map), s->map_bytes);
  close(s->fd);
  delete s;
}

// Gather rows `indices[0..count)` into `out` ([count, seq_len] int32),
// split across up to `n_threads` threads (0 -> hardware concurrency).
int ts_gather(const Shard* s, const uint64_t* indices, uint64_t count,
              int32_t* out, int n_threads) {
  const uint64_t row_bytes = s->seq_len * sizeof(int32_t);
  for (uint64_t i = 0; i < count; ++i) {
    if (indices[i] >= s->n_seqs) return -1;
  }
  unsigned hw = std::thread::hardware_concurrency();
  unsigned workers = n_threads > 0 ? static_cast<unsigned>(n_threads)
                                   : (hw ? hw : 1);
  if (workers > count) workers = static_cast<unsigned>(count ? count : 1);
  if (workers <= 1) {
    for (uint64_t i = 0; i < count; ++i) {
      memcpy(out + i * s->seq_len, s->data + indices[i] * s->seq_len, row_bytes);
    }
    return 0;
  }
  std::vector<std::thread> threads;
  std::atomic<uint64_t> next{0};
  constexpr uint64_t kChunk = 64;
  for (unsigned t = 0; t < workers; ++t) {
    threads.emplace_back([&]() {
      for (;;) {
        uint64_t begin = next.fetch_add(kChunk);
        if (begin >= count) break;
        uint64_t end = begin + kChunk < count ? begin + kChunk : count;
        for (uint64_t i = begin; i < end; ++i) {
          memcpy(out + i * s->seq_len, s->data + indices[i] * s->seq_len,
                 row_bytes);
        }
      }
    });
  }
  for (auto& th : threads) th.join();
  return 0;
}

// Deterministic permutation of [0, n) from (seed, epoch, worker):
// Fisher-Yates driven by splitmix64. Identical output on every host.
void ts_shuffled_indices(uint64_t n, uint64_t seed, uint64_t epoch,
                         uint64_t worker, uint64_t* out) {
  for (uint64_t i = 0; i < n; ++i) out[i] = i;
  uint64_t s = seed * 0x9e3779b97f4a7c15ULL + epoch * 0xbf58476d1ce4e5b9ULL +
               worker * 0x94d049bb133111ebULL + 1;
  for (uint64_t i = n; i > 1; --i) {
    uint64_t j = splitmix64(s) % i;
    uint64_t tmp = out[i - 1];
    out[i - 1] = out[j];
    out[j] = tmp;
  }
}

}  // extern "C"
